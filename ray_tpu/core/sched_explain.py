"""Scheduler "explain" plane: typed pending reasons + sched metrics.

The control plane decides where work goes and why it waits; this module
makes those decisions *inspectable* instead of inferred:

* :class:`PendingReason` — the closed set of reasons a task/actor/PG can
  be in a non-running state.  Reason stamps ride the existing task-event
  plane as ``state="PENDING"`` events carrying ``reason=<constant>``, so
  the timeline, ``state.summarize_tasks()["pending_reasons"]`` and
  ``raytpu explain`` all read the same trail.  Stamps MUST use these
  constants — a lint (tests/test_metric_naming.py) rejects free-form
  strings, which would otherwise become unbounded label values.
* Decision records — ``pick_node``/``pack_bundles`` callers emit one
  structured record per scheduling decision (candidates considered,
  per-node rejection cause, outcome) into a bounded ring in the GCS
  (``add_sched_decisions`` / ``get_sched_decisions`` / ``explain``).
* ``sched_metrics_enabled`` — the single kill switch for every
  ``raytpu_sched_*`` / ``raytpu_loop_*`` / ``raytpu_gcs_*`` series
  (PR-2 registry discipline: off, hot paths pay one boolean check).

Reference: the Ray paper (1712.05889) makes bottom-up scheduling + GCS
the heart of the system and debuggability first-class; Podracer
(2104.06272) demands the control plane stay *provably* cheap — both need
"why is my task pending" answerable from the runtime.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .config import get_config


class PendingReason:
    """Closed vocabulary of non-running-state reasons.

    These are EVENT FIELD values and metric tag values — the set is the
    cardinality bound, so new reasons are added here (and to the state
    machine diagram in ARCHITECTURE.md), never inlined at a call site.
    """

    #: waiting on a dependency that is not schedulable work on this node:
    #: an actor call parked while its actor is still being placed/restarted
    WAITING_DEPS = "WAITING_DEPS"
    #: the owner's waitable admission gate parked the submitting thread
    #: (``submit_inflight_limit`` reached)
    ADMISSION_GATE = "ADMISSION_GATE"
    #: a lease request is parked in some agent's bounded lease queue
    #: (saturated node, request queued behind running leases)
    LEASE_QUEUED = "LEASE_QUEUED"
    #: an agent answered the lease request with a backpressure reply
    #: (queue at ``lease_queue_max_depth``, or the node is draining)
    BACKPRESSURED = "BACKPRESSURED"
    #: no alive node can satisfy the resource shape (infeasible now)
    NO_RESOURCES = "NO_RESOURCES"
    #: the only node(s) that could run it are draining (preemption notice)
    NODE_DRAINING = "NODE_DRAINING"
    #: scheduled against a placement group that is not CREATED yet
    PG_PENDING = "PG_PENDING"
    #: a warm-path submission hit SpecCacheMiss and is resending the full
    #: spec template before dispatch
    SPEC_CACHE_RESEND = "SPEC_CACHE_RESEND"

    ALL = frozenset({
        "WAITING_DEPS", "ADMISSION_GATE", "LEASE_QUEUED", "BACKPRESSURED",
        "NO_RESOURCES", "NODE_DRAINING", "PG_PENDING", "SPEC_CACHE_RESEND",
    })


#: per-node rejection causes a decision record may carry (the bounded
#: vocabulary ``pick_node``/``pack_bundles`` explain dicts use)
REJECT_CAUSES = ("dead", "draining", "resources", "affinity")

#: per-record cap on the {node: cause} rejection map — records live in a
#: 2048-deep ring and ship whole over RPC, so a 1000-node cluster must
#: not put 1000 entries in every one
REJECTED_SAMPLE_MAX = 8


def bound_rejected(rejected: Optional[Dict[str, str]]) -> dict:
    """Shrink a per-node rejection map to record size: a bounded sample
    of ``{node: cause}`` plus, when truncated, a full per-cause count
    rollup (``rejected_counts``) so nothing is silently dropped."""
    rejected = rejected or {}
    if len(rejected) <= REJECTED_SAMPLE_MAX:
        return {"rejected": rejected}
    sample = dict(list(rejected.items())[:REJECTED_SAMPLE_MAX])
    counts: Dict[str, int] = {}
    for cause in rejected.values():
        counts[cause] = counts.get(cause, 0) + 1
    return {"rejected": sample, "rejected_counts": counts,
            "rejected_total": len(rejected)}


def reason_for_no_node(explain: Optional[dict]) -> str:
    """Map a failed pick's explain record to the typed pending reason: a
    ``draining`` rejection cause marks a node that COULD have hosted the
    shape but is routed around by its preemption notice (infeasible
    nodes read ``resources`` whatever their drain state), so its
    presence means the drain is what is blocking the task
    (NODE_DRAINING); otherwise the shape simply has nowhere to run right
    now (NO_RESOURCES)."""
    rejected = (explain or {}).get("rejected") or {}
    if "draining" in set(rejected.values()):
        return PendingReason.NODE_DRAINING
    return PendingReason.NO_RESOURCES


# ------------------------------------------------------------- kill switch

_enabled_cache: tuple = (None, False)


def enabled() -> bool:
    """One cached boolean per Config identity — the hot-path check."""
    global _enabled_cache
    cfg = get_config()
    if _enabled_cache[0] is not cfg:
        _enabled_cache = (cfg, bool(getattr(cfg, "sched_metrics_enabled",
                                            False)))
    return _enabled_cache[1]


# ----------------------------------------------------------- sched metrics
#
# Lazy singletons on the PR-2 registry.  Tag keys are bounded by the
# allowlist lint: process / method / reason / node only.

def _build_owner_metrics():
    from ray_tpu.util.metrics import Histogram
    return {
        "serialize": Histogram(
            "raytpu_sched_owner_serialize_seconds",
            "owner-side spec wire-encoding (pickling) time per push batch"),
        "flush": Histogram(
            "raytpu_sched_owner_flush_seconds",
            "owner-side submit-buffer flush (pool routing + pump) time"),
    }


_owner_metrics_get = None


def owner_metrics() -> Optional[Dict[str, Any]]:
    global _owner_metrics_get
    if not enabled():
        return None
    if _owner_metrics_get is None:
        from ray_tpu.util.metrics import lazy
        _owner_metrics_get = lazy(_build_owner_metrics)
    return _owner_metrics_get()


def _build_backpressure_counter():
    from ray_tpu.util.metrics import Counter
    return Counter(
        "raytpu_sched_backpressure_total",
        "lease requests answered with backpressure, by node and reason",
        tag_keys=("node", "reason"))


_bp_counter_get = None


def backpressure_counter():
    global _bp_counter_get
    if not enabled():
        return None
    if _bp_counter_get is None:
        from ray_tpu.util.metrics import lazy
        _bp_counter_get = lazy(_build_backpressure_counter)
    return _bp_counter_get()


def _build_gcs_handler_hist():
    from ray_tpu.util.metrics import Histogram
    return Histogram(
        "raytpu_gcs_handler_seconds",
        "GCS handler BUSY seconds per invocation (synchronous-segment "
        "time the handler blocked that GCS process's loop; awaits "
        "excluded, so long-polls read near zero).  ``shard`` is bounded "
        "by the process count: \"router\" or the shard index.",
        tag_keys=("method", "shard"))


_gcs_hist_get = None


def gcs_handler_hist():
    global _gcs_hist_get
    if not enabled():
        return None
    if _gcs_hist_get is None:
        from ray_tpu.util.metrics import lazy
        _gcs_hist_get = lazy(_build_gcs_handler_hist)
    return _gcs_hist_get()
