"""Public API: init/shutdown/remote/get/put/wait/kill — reference:
``python/ray/_private/worker.py`` (``ray.init`` :1127, ``get`` :2451, ``put`` :2580,
``wait`` :2643).

``init()`` with no address boots an in-process head node: the GCS-equivalent control
plane and the node agent run on the background IO loop of the driver process (the
reference runs them as separate processes started by ``_private/node.py:1395``; here the
head is embedded, and extra nodes — or a standalone head via ``ray_tpu.core.cluster`` —
are separate processes).  Worker processes are always real subprocesses.
"""

from __future__ import annotations

import asyncio
import atexit
import inspect
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from .actor import (ActorClass, ActorHandle, exit_actor,  # noqa: F401
                    get_actor)
from .common import GetTimeoutError, TaskError  # noqa: F401
from .config import Config, get_config, set_config
from .core_worker import CoreWorker, global_worker, global_worker_or_none
from .gcs import GcsServer
from .ids import JobID
from .node_agent import NodeAgent
from .object_ref import ObjectRef
from .remote_function import RemoteFunction
from .rpc import run_async


class _GlobalState:
    def __init__(self):
        self.gcs_server: Optional[GcsServer] = None
        self.node_agent: Optional[NodeAgent] = None
        self.worker: Optional[CoreWorker] = None
        self.gcs_address: Optional[str] = None
        self.session_dir: Optional[str] = None
        # IO-loop lanes the embedded control plane runs on (config
        # control_plane_io_lanes; 0 = the shared default loop)
        self.gcs_lane = 0
        self.agent_lane = 0


_state = _GlobalState()


def is_initialized() -> bool:
    return global_worker_or_none() is not None


def init(address: Optional[str] = None,
         *,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         labels: Optional[Dict[str, str]] = None,
         object_store_memory: int = 0,
         namespace: Optional[str] = None,
         ignore_reinit_error: bool = False,
         log_to_driver: bool = True,
         runtime_env: Optional[Dict[str, Any]] = None,
         _system_config: Optional[Dict[str, Any]] = None,
         worker_env: Optional[Dict[str, str]] = None) -> dict:
    """Start (or connect to) a cluster and attach this process as the driver.

    ``address``: None/"local" boots an in-process GCS + node agent;
    "auto" reads ``RAYTPU_GCS_ADDRESS``; "host:port" joins a running
    cluster directly.  There is deliberately no separate ``ray://`` client
    proxy (reference: ``python/ray/util/client``): that proxy exists because
    the reference's driver embeds a heavyweight C++ CoreWorker that can't
    run outside the cluster, whereas this driver is an ordinary RPC peer —
    a remote process passes the GCS address and IS a fully-featured driver
    (``raytpu submit`` covers the fire-and-forget case).
    """
    if is_initialized():
        if ignore_reinit_error:
            return {"address": _state.gcs_address}
        raise RuntimeError("ray_tpu.init() called twice "
                           "(pass ignore_reinit_error=True to ignore)")
    if runtime_env:
        # validate BEFORE booting anything: raising after processes start
        # would leave a half-initialized session with no atexit cleanup
        from . import runtime_env as renv
        renv.validate(runtime_env)
    if _system_config:
        set_config(Config.from_env(_system_config))
    # session boundary: the fault injector re-derives from the (possibly
    # just-overridden) config/env instead of keeping a stale cached one
    from .chaos import reset as _reset_chaos
    _reset_chaos()
    session_dir = os.path.join(
        "/tmp/raytpu", f"session-{int(time.time() * 1000)}-{os.getpid()}")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    _state.session_dir = session_dir

    # With control_plane_io_lanes the embedded GCS and agent each get
    # their OWN IO-loop thread: GCS handlers, agent lease/store handlers,
    # and the owner submission path stop contending for one loop (the
    # single-process head's structural ceiling — ROADMAP item 5).
    use_lanes = get_config().control_plane_io_lanes
    _state.gcs_lane = "cp-gcs" if use_lanes else 0
    _state.agent_lane = "cp-agent" if use_lanes else 0
    if address in (None, "local"):
        gcs = GcsServer(session_dir=session_dir)
        run_async(gcs.start(), lane=_state.gcs_lane)
        _state.gcs_server = gcs
        gcs_address = gcs.address
    else:
        gcs_address = os.environ.get("RAYTPU_GCS_ADDRESS", "") if address == "auto" \
            else address
        if not gcs_address:
            raise ValueError("address='auto' but RAYTPU_GCS_ADDRESS is not set")
    _state.gcs_address = gcs_address
    os.environ["RAYTPU_GCS_ADDRESS"] = gcs_address

    # Head-resident node agent (every driver process gets a local node unless it
    # explicitly connects to an existing cluster with its own nodes).
    agent = None
    if address in (None, "local"):
        agent = NodeAgent(gcs_address, num_cpus=num_cpus, num_tpus=num_tpus,
                          resources=resources, labels=labels,
                          session_dir=session_dir, worker_env=worker_env,
                          object_store_memory=object_store_memory)
        run_async(agent.start(), lane=_state.agent_lane)
        _state.node_agent = agent

    worker = CoreWorker(mode="driver", gcs_address=gcs_address,
                        agent_address=agent.address if agent else _pick_agent(gcs_address),
                        node_id=agent.node_id.hex() if agent else None,
                        session_dir=session_dir)
    worker.start()
    job_hex = run_async(worker.gcs.call_retry(
        "register_job", metadata={"namespace": namespace or "default"}))
    worker.job_id = JobID.from_hex(job_hex)
    _state.worker = worker
    if runtime_env:
        # ship py_modules/working_dir/env_vars to every worker of this job
        # (reference: runtime_env packaging via the GCS)
        from . import runtime_env as renv
        renv.publish(
            lambda *a, **kw: run_async(worker.gcs.call(*a, **kw)),
            worker.job_id.hex(), runtime_env)
    if log_to_driver:
        _start_log_subscriber(worker)
    # Flush library usages buffered before init (reference:
    # put_pre_init_usage_stats) — recording itself never does I/O.
    from ray_tpu.util import usage_stats
    usage_stats.flush()
    atexit.register(shutdown)
    return {"address": gcs_address, "session_dir": session_dir,
            "node_id": worker.node_id}


def _start_log_subscriber(worker):
    """Stream worker stdout/stderr to this driver (reference:
    log_monitor.py:103 + worker.print_logs): a daemon thread long-polls the
    GCS ``worker_logs`` topic and prefixes each line with its origin."""
    import sys
    import threading

    from .rpc import RpcClient

    def loop():
        client = RpcClient(worker.gcs_address)
        cursor = -1  # -1: start from "now" (first poll returns current seq)
        try:
            cursor, _ = run_async(client.call(
                "pubsub_poll", topics=["worker_logs"], cursor=1 << 60,
                timeout=0.01))
        except Exception:
            cursor = 0
        while _state.worker is worker:
            try:
                cursor, events = run_async(
                    client.call("pubsub_poll", topics=["worker_logs"],
                                cursor=cursor, timeout=5.0),
                    timeout=10.0)
            except Exception:
                time.sleep(1.0)
                continue
            for _seq, _topic, payload in events:
                for entry in payload.get("batch", []):
                    tag = f"({payload.get('node', '?')}:" \
                          f"{entry.get('worker', '?')})"
                    for line in entry.get("lines", []):
                        print(f"{tag} {line}", file=sys.stderr)
        try:
            run_async(client.close(), timeout=2)
        except Exception:
            pass

    threading.Thread(target=loop, daemon=True,
                     name="log-subscriber").start()


def _pick_agent(gcs_address: str) -> Optional[str]:
    """When connecting to an existing cluster, attach to the least-loaded node's
    agent for object-store access."""
    from .rpc import RpcClient
    client = RpcClient(gcs_address)
    view = run_async(client.call_retry("get_cluster_view",
                                       _idempotent=False))
    run_async(client.close())
    alive = {k: v for k, v in view.items() if v.get("alive", True)}
    if not alive:
        return None
    nid = sorted(alive)[0]
    return alive[nid]["address"]


def shutdown():
    w = _state.worker
    if w is not None:
        try:
            # Persist the usage rollup next to the session logs while the
            # GCS is still up (reference: UsageStatsToWrite).  Short
            # timeout: this also runs from atexit against possibly-dead
            # clusters.  Forget the flushed state — a later init must
            # re-report even to a cluster reusing this GCS address.
            from ray_tpu.util import usage_stats
            usage_stats.write_report(timeout_s=1.5)
            usage_stats.forget_flushed_state()
        except Exception:
            pass
        try:
            run_async(w.gcs.call("finish_job", job_id=w.job_id.hex()), timeout=2)
        except Exception:
            pass
        w.shutdown()
        _state.worker = None
    if _state.node_agent is not None:
        try:
            run_async(_state.node_agent.stop(), timeout=5,
                      lane=_state.agent_lane)
        except Exception:
            pass
        _state.node_agent = None
        _state.agent_lane = 0
    if _state.gcs_server is not None:
        try:
            run_async(_state.gcs_server.stop(), timeout=5,
                      lane=_state.gcs_lane)
        except Exception:
            pass
        _state.gcs_server = None
        _state.gcs_lane = 0
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass
    from .chaos import reset as reset_chaos
    from .config import reset_config
    reset_config()
    reset_chaos()  # next init re-derives the injector from config/env


# ---------------------------------------------------------------------------
# Core verbs
# ---------------------------------------------------------------------------

def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("ray_tpu.put() does not accept ObjectRefs")
    return global_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    if isinstance(refs, (list, tuple)):
        bad = [r for r in refs if not isinstance(r, ObjectRef)]
        if bad:
            raise TypeError(f"ray_tpu.get() takes ObjectRefs, got {type(bad[0])}")
        return global_worker().get(list(refs), timeout=timeout)
    if not isinstance(refs, ObjectRef):
        raise TypeError(f"ray_tpu.get() takes an ObjectRef, got {type(refs)}")
    return global_worker().get(refs, timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    if isinstance(refs, ObjectRef):
        raise TypeError("ray_tpu.wait() takes a list of ObjectRefs")
    return global_worker().wait(list(refs), num_returns=num_returns, timeout=timeout)


async def get_async(ref: ObjectRef):
    return await global_worker().get_async(ref)


def as_future(ref: ObjectRef):
    import concurrent.futures
    fut: concurrent.futures.Future = concurrent.futures.Future()

    async def _resolve():
        try:
            fut.set_result(await global_worker().get_async(ref))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)

    from .rpc import get_loop
    asyncio.run_coroutine_threadsafe(_resolve(), get_loop())
    return fut


def kill(actor: ActorHandle, *, no_restart: bool = True):
    global_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    """Cooperative cancellation: drop the task from lease queues if it has not
    been dispatched yet.  Runs on the IO loop — the lease pools are loop-
    confined state (reference: CancelTask RPC is best-effort there too)."""
    w = global_worker()
    tid = ref.id.task_id()

    async def _cancel():
        for pool in w.lease_pools.values():
            for spec in list(pool.queue):
                if spec.task_id == tid:
                    pool.queue.remove(spec)
                    w.task_manager.fail(
                        tid, asyncio.CancelledError("task cancelled"))
                    return True
        return False

    return run_async(_cancel())


def remote(*args, **options):
    """@remote decorator for functions and classes (reference: ray.remote)."""
    def make(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and not options and (inspect.isclass(args[0])
                                           or callable(args[0])):
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")
    return make


def method(**options):
    """@method decorator for actor methods (num_returns), reference ray.method."""
    def deco(fn):
        fn.__ray_method_options__ = options
        return fn
    return deco


# ---------------------------------------------------------------------------
# Introspection
# ---------------------------------------------------------------------------

def nodes() -> List[dict]:
    view = run_async(global_worker().gcs.call("get_cluster_view"))
    return [{"NodeID": nid, "Alive": d["alive"], "Resources": d["total"],
             "Available": d["available"], "Labels": d.get("labels", {}),
             "AgentAddress": d["address"]} for nid, d in view.items()]


def cluster_resources() -> Dict[str, float]:
    out: Dict[str, float] = {}
    for n in nodes():
        if n["Alive"]:
            for k, v in n["Resources"].items():
                out[k] = out.get(k, 0.0) + v
    return out


def available_resources() -> Dict[str, float]:
    out: Dict[str, float] = {}
    for n in nodes():
        if n["Alive"]:
            for k, v in n["Available"].items():
                out[k] = out.get(k, 0.0) + v
    return out


def timeline() -> List[dict]:
    return run_async(global_worker().gcs.call("list_task_events", limit=10000))
