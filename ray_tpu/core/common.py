"""Common wire types: TaskSpec, resource sets, scheduling strategies, errors.

TaskSpec mirrors the reference's ``TaskSpecification``
(``src/ray/common/task/task_spec.h`` / ``src/ray/protobuf/common.proto``): one message
covers normal tasks, actor-creation tasks, and actor method calls.  Functions travel by
content hash through the GCS function registry (reference:
``python/ray/_private/function_manager.py`` — ships pickled defs via GCS KV; workers
lazy-import), so the spec itself stays small.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID


# ---------------------------------------------------------------------------
# Scheduling strategies (reference: python/ray/util/scheduling_strategies.py)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NodeAffinitySchedulingStrategy:
    node_id: str  # hex
    soft: bool = False


@dataclass(frozen=True)
class PlacementGroupSchedulingStrategy:
    placement_group: Any  # PlacementGroup handle
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass(frozen=True)
class NodeLabelSchedulingStrategy:
    hard: Dict[str, List[str]] = field(default_factory=dict)
    soft: Dict[str, List[str]] = field(default_factory=dict)


SchedulingStrategy = Any  # "DEFAULT" | "SPREAD" | one of the dataclasses above


# ---------------------------------------------------------------------------
# Task spec
# ---------------------------------------------------------------------------

# Sentinel num_returns for streaming-generator tasks (``num_returns="streaming"``):
# return count is dynamic; yields become owner-owned objects as they arrive.
STREAMING_RETURNS = -1

#: inlined-args blobs at least this large ship as pickle-5 out-of-band
#: buffers.  Tied to the RPC layer's vectored-frame threshold: a
#: PickleBuffer below rpc._VEC_MIN_BUF would be wrapped but still
#: serialized in-band, silently defeating the point.
from .rpc import _VEC_MIN_BUF as _VECTORED_ARGS_MIN


def _rebuild_task_spec(kw: dict, args_buf) -> "TaskSpec":
    # Out-of-band receive hands us the transport's bytes object directly
    # (zero-copy); in-band protocol-5 decodes to bytes as well.  Coerce any
    # other buffer type so later re-pickles (lineage copies at protocol 4)
    # keep working.
    kw["args"] = args_buf if isinstance(args_buf, bytes) else bytes(args_buf)
    return TaskSpec(**kw)


@dataclass(slots=True)
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    name: str
    # function: registered blob hash; actor methods reference the actor's class
    fn_id: Optional[bytes]
    # serialized (args, kwargs) — SerializedObject.to_bytes(); top-level refs
    # are wrapped in _TopLevelRef markers inside.
    args: bytes
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    owner: str = ""                 # rpc address of owner core worker
    scheduling_strategy: SchedulingStrategy = "DEFAULT"
    max_retries: int = 0
    retry_count: int = 0
    retry_exceptions: bool = False
    runtime_env: Optional[dict] = None
    # actor creation
    is_actor_creation: bool = False
    actor_id: Optional[ActorID] = None
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    is_async_actor: bool = False
    actor_name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None    # None (job-scoped) | "detached"
    # actor method call
    is_actor_task: bool = False
    actor_method: Optional[str] = None
    seq_no: int = 0
    #: streaming generators: pause the producer once this many yields are
    #: unconsumed (0 = unbounded; reference: _generator_backpressure_num_objects)
    generator_backpressure: int = 0
    #: propagated trace context (trace_id, parent_span_id) — reference:
    #: util/tracing/tracing_helper.py serialized span context in the spec
    trace_ctx: Optional[tuple] = None
    # bookkeeping
    submitted_at: float = field(default_factory=time.time)

    def scheduling_key(self) -> tuple:
        """Tasks with the same key can reuse the same leased worker
        (reference: SchedulingKey in direct_task_transport.h:151).  The
        runtime env is part of worker identity: a pip env means a dedicated
        interpreter, so different envs must never share a lease pool."""
        env_key = None
        if self.runtime_env:
            env_key = repr(sorted(
                (k, repr(v)) for k, v in self.runtime_env.items()))
        return (self.fn_id, tuple(sorted(self.resources.items())),
                repr(self.scheduling_strategy), env_key)

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_task_return(self.task_id, i) for i in range(self.num_returns)]

    def __reduce_ex__(self, protocol):
        # Large inlined args ride out-of-band at protocol 5+ so a
        # push_task_batch carrying a big serialized argument blob never
        # concatenates it through the frame's pickle stream (see the RPC
        # layer's vectored frames).  Protocol < 5 (lineage deep-copies via
        # pickle.dumps default) keeps the plain dataclass reduce.
        if protocol >= 5 and isinstance(self.args, bytes) \
                and len(self.args) >= _VECTORED_ARGS_MIN:
            import pickle as _pickle
            kw = {n: getattr(self, n) for n in SPEC_FIELDS if n != "args"}
            return (_rebuild_task_spec, (kw, _pickle.PickleBuffer(self.args)))
        # object., not super().: @dataclass(slots=True) rebuilds the class,
        # so the zero-arg super() closure would point at the discarded
        # pre-slots class and raise on every pickle.
        return object.__reduce_ex__(self, protocol)


#: TaskSpec field names in declaration order — the slotted class has no
#: ``__dict__``, so everything that used to iterate ``spec.__dict__``
#: (template split, prototype clone) iterates this tuple instead.
import dataclasses as _dataclasses
SPEC_FIELDS: Tuple[str, ...] = tuple(
    f.name for f in _dataclasses.fields(TaskSpec))

#: Fields that vary per call — everything else is template-invariant for
#: one (function, options) pair.  The template cache (spec_cache.py) and
#: the owner's template-clone fast path both key off this split.
VOLATILE_FIELDS: Tuple[str, ...] = (
    "task_id", "args", "retry_count", "seq_no", "trace_ctx", "submitted_at")

TEMPLATE_FIELDS: Tuple[str, ...] = tuple(
    n for n in SPEC_FIELDS if n not in VOLATILE_FIELDS)

# Generated field-by-field copies (slot loads/stores, no dict machinery) —
# the clone primitives under the receiver's prototype-interner decode and
# the owner's template-clone submission fast path.  copy_template_into
# skips the volatile fields its callers store immediately after.
_ns: Dict[str, Any] = {}
exec("def copy_spec_into(src, dst):\n"
     + "".join(f"    dst.{n} = src.{n}\n" for n in SPEC_FIELDS), _ns)
exec("def copy_template_into(src, dst):\n"
     + "".join(f"    dst.{n} = src.{n}\n" for n in TEMPLATE_FIELDS), _ns)
copy_spec_into = _ns["copy_spec_into"]
copy_template_into = _ns["copy_template_into"]
del _ns


# ---------------------------------------------------------------------------
# TaskSpec free-list (submission fast path)
#
# Submitted specs are recycled at terminal completion (TaskManager.complete,
# when the spec escaped into neither lineage nor a stream) and re-acquired
# by the next warm ``.remote()`` — a steady-state submission allocates no
# new spec object.  deque append/pop are single-bytecode atomic under the
# GIL, so the driver thread acquires while the IO loop recycles without a
# lock.  Templates cached on RemoteFunction/ActorMethod handles are built
# OUTSIDE the free-list and never submitted, so no live template can be
# handed out twice.
# ---------------------------------------------------------------------------

_SPEC_FREELIST: List[TaskSpec] = []
#: exact counters (submission-plane observability: free-list hit rate)
spec_freelist_hits = 0
spec_freelist_misses = 0


def spec_from_freelist() -> TaskSpec:
    """A recycled (stale-fielded) spec, or a fresh uninitialized one."""
    global spec_freelist_hits, spec_freelist_misses
    try:
        spec = _SPEC_FREELIST.pop()
        spec_freelist_hits += 1
        return spec
    except IndexError:
        spec_freelist_misses += 1
        return TaskSpec.__new__(TaskSpec)


def recycle_spec(spec: TaskSpec, limit: int) -> None:
    if len(_SPEC_FREELIST) < limit:
        _SPEC_FREELIST.append(spec)


def build_spec_from_template(tmpl: TaskSpec, task_id: TaskID, args: bytes,
                             trace_ctx: Optional[tuple]) -> TaskSpec:
    """Warm-path spec build: clone the handle's invariant template into a
    free-list spec and store only the per-call fields — the allocation-free
    replacement for the 28-kwarg dataclass ctor."""
    spec = spec_from_freelist()
    copy_template_into(tmpl, spec)
    spec.task_id = task_id
    spec.args = args
    spec.retry_count = 0
    spec.seq_no = 0
    spec.trace_ctx = trace_ctx
    spec.submitted_at = time.time()
    return spec


@dataclass
class _TopLevelRef:
    """Marker for a top-level ObjectRef argument: resolved to its value before the
    user function runs (nested refs are passed through as refs — ray semantics)."""
    ref: Any


# ---------------------------------------------------------------------------
# Errors (reference: python/ray/exceptions.py)
# ---------------------------------------------------------------------------

class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """Wraps an exception raised inside a task; re-raised at ray.get."""

    def __init__(self, cause: BaseException, task_name: str = "", remote_tb: str = ""):
        self.cause = cause
        self.task_name = task_name
        self.remote_traceback = remote_tb
        super().__init__(f"task {task_name!r} failed: {type(cause).__name__}: {cause}"
                         + (f"\n--- remote traceback ---\n{remote_tb}" if remote_tb else ""))

    def __reduce__(self):
        # args holds the formatted message, not the ctor signature — without
        # this, a pickle round-trip re-feeds the message as `cause`.
        return (type(self), (self.cause, self.task_name, self.remote_traceback))


class RuntimeEnvSetupError(RayTpuError):
    """The task's runtime environment could not be built (e.g. pip install
    failed) — deterministic, so the task fails instead of retrying
    (reference: ray.exceptions.RuntimeEnvSetupError)."""


class WorkerCrashedError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """The node's memory monitor killed the worker running this task
    (reference: ray.exceptions.OutOfMemoryError + memory_monitor.h:52).
    Retriable: the retry runs under relieved memory pressure."""


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id=None, msg: str = ""):
        self.actor_id = actor_id
        super().__init__(msg or f"actor {actor_id} died")

    def __reduce__(self):
        return (type(self), (self.actor_id, str(self)))


class ActorUnavailableError(RayTpuError):
    pass


class ObjectLostError(RayTpuError):
    def __init__(self, object_id, msg=""):
        self.object_id = object_id
        super().__init__(msg or f"object {object_id} lost and could not be reconstructed")

    def __reduce__(self):
        return (type(self), (self.object_id, str(self)))


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------

def detect_node_resources(num_cpus: Optional[float] = None,
                          num_tpus: Optional[float] = None,
                          resources: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    """Autodetect CPU / TPU resources for a node.

    TPU detection follows the reference's approach
    (``python/ray/_private/accelerator.py:35-42,153`` — counts ``/dev/accel*`` chips,
    honours ``TPU_VISIBLE_CHIPS``) without importing jax.
    """
    import os
    out: Dict[str, float] = dict(resources or {})
    if num_cpus is None:
        num_cpus = os.cpu_count() or 1
    out["CPU"] = float(num_cpus)
    if num_tpus is None:
        visible = os.environ.get("TPU_VISIBLE_CHIPS")
        if visible:
            num_tpus = len([c for c in visible.split(",") if c.strip()])
        else:
            try:
                num_tpus = len([d for d in os.listdir("/dev") if d.startswith("accel")])
            except OSError:
                num_tpus = 0
    if num_tpus:
        out["TPU"] = float(num_tpus)
    try:
        mem = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
        out.setdefault("memory", float(int(mem * 0.7)))
    except (ValueError, OSError):
        pass
    return out


class ResourceSet:
    """Float resource accounting with exact add/subtract semantics."""

    __slots__ = ("_r",)

    def __init__(self, amounts: Dict[str, float] | None = None):
        self._r = {k: float(v) for k, v in (amounts or {}).items() if v}

    def to_dict(self) -> Dict[str, float]:
        return dict(self._r)

    def get(self, k: str) -> float:
        return self._r.get(k, 0.0)

    def set(self, k: str, v: float):
        """Set one resource's amount; 0 removes the key (dynamic-resource
        deletion semantics)."""
        if v:
            self._r[k] = float(v)
        else:
            self._r.pop(k, None)

    def can_fit(self, demand: Dict[str, float]) -> bool:
        return all(self._r.get(k, 0.0) + 1e-9 >= v for k, v in demand.items() if v > 0)

    def acquire(self, demand: Dict[str, float]) -> bool:
        if not self.can_fit(demand):
            return False
        for k, v in demand.items():
            if v > 0:
                self._r[k] = self._r.get(k, 0.0) - v
        return True

    def release(self, demand: Dict[str, float]):
        for k, v in demand.items():
            if v > 0:
                self._r[k] = self._r.get(k, 0.0) + v

    def force_acquire(self, demand: Dict[str, float]):
        """Subtract without feasibility check — used when a blocked worker
        resumes and reclaims its released resources (temporary oversubscription,
        like the reference raylet's unblock path)."""
        for k, v in demand.items():
            if v > 0:
                self._r[k] = self._r.get(k, 0.0) - v

    def utilization(self, total: "ResourceSet") -> float:
        """Max utilization across resources present in `total` (critical resource)."""
        u = 0.0
        for k, tot in total._r.items():
            if tot > 0:
                u = max(u, 1.0 - self._r.get(k, 0.0) / tot)
        return u
