"""@remote functions (reference: ``python/ray/remote_function.py`` — RemoteFunction :40,
``_remote`` :257 builds the TaskSpec options).

Functions ship by content hash through the GCS KV function registry once per process
(reference: ``python/ray/_private/function_manager.py``); the TaskSpec carries only the
hash.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional

from . import serialization
from .common import (STREAMING_RETURNS, PlacementGroupSchedulingStrategy,
                     TaskSpec, _TopLevelRef, build_spec_from_template,
                     copy_spec_into)
from .config import get_config
from .ids import TaskID
from .object_ref import ObjectRef
from .rpc import run_async

# Bound on first .remote() call (core_worker imports this module, so a
# top-level import would be circular).
_global_worker = None


def _wrap_args(args, kwargs):
    """Wrap top-level ObjectRefs so the executor resolves them to values
    (nested refs pass through as refs — ray argument semantics)."""
    wargs = [(_TopLevelRef(a) if isinstance(a, ObjectRef) else a) for a in args]
    wkwargs = {k: (_TopLevelRef(v) if isinstance(v, ObjectRef) else v)
               for k, v in kwargs.items()}
    return wargs, wkwargs


def _current_trace_ctx():
    from ray_tpu.util import tracing
    return tracing.current_context()


_EMPTY_ARGS: Optional[bytes] = None


def serialize_args(args, kwargs):
    if not args and not kwargs:
        # No-arg calls (pings, control-plane methods) skip the pickler: the
        # canonical empty blob is byte-identical on every call, so the
        # executor can match it and skip deserialization too.
        global _EMPTY_ARGS
        if _EMPTY_ARGS is None:
            _EMPTY_ARGS = serialization.serialize(([], {})).to_bytes()
        return _EMPTY_ARGS, []
    wargs, wkwargs = _wrap_args(args, kwargs)
    so = serialization.serialize((wargs, wkwargs))
    return so.to_bytes(), list(so.contained_refs)


class RemoteFunction:
    def __init__(self, fn, default_options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._opts = dict(default_options or {})
        self._blob: Optional[bytes] = None
        self._fn_id: Optional[bytes] = None
        self._captured_refs: list = []
        self._registered_in: set = set()
        #: warm-path spec template: every call-invariant field of this
        #: (function, options) pair, built once on the first .remote() and
        #: cloned (pooled slot copy + volatile stores) on every later call.
        #: Keyed to the worker/config generation it was built under —
        #: reinit or set_config() rebuilds.  options() returns a NEW
        #: RemoteFunction, so the template is per-(fn, options) by design.
        self._spec_tmpl: Optional[TaskSpec] = None
        self._spec_tmpl_key: Optional[tuple] = None
        self.__name__ = getattr(fn, "__name__", "anonymous")

    # -- registration ------------------------------------------------------

    def _ensure_registered(self, worker) -> bytes:
        if self._blob is None:
            self._blob, self._captured_refs = \
                serialization.dumps_function_with_refs(self._fn)
            self._fn_id = hashlib.sha1(self._blob).digest()[:16]
        key = id(worker)
        if key not in self._registered_in:
            run_async(worker.gcs.call_retry(
                "kv_put", ns="funcs", key=self._fn_id.hex(),
                value=self._blob, overwrite=False))
            self._registered_in.add(key)
        return self._fn_id

    # -- public API --------------------------------------------------------

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._opts)
        merged.update(opts)
        rf = RemoteFunction(self._fn, merged)
        rf._blob, rf._fn_id = self._blob, self._fn_id
        rf._captured_refs = self._captured_refs
        return rf

    def bind(self, *args, **kwargs):
        """Lazy DAG node instead of immediate submission (reference:
        dag/function_node.py)."""
        from ..dag import FunctionNode
        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        global _global_worker
        if _global_worker is None:  # deferred: core_worker imports us
            from .core_worker import global_worker as _global_worker
        w = _global_worker()
        cfg = get_config()
        args_blob, arg_refs = serialize_args(args, kwargs)
        # Closure-captured refs are data dependencies exactly like argument
        # refs: they must be pinned until the task finishes, and the batch
        # scheduler must not coalesce this task with their producers.
        if self._captured_refs:
            arg_refs = arg_refs + self._captured_refs
        # Warm path: every call-invariant field comes from the cached
        # template via a pooled slot copy — no per-call resources dict, no
        # option lookups, no TaskSpec ctor.  The key pins the template to
        # this worker AND config generation (registration happened when the
        # template was built for this worker; set_config() swaps the config
        # object, invalidating templates whose fields read old defaults).
        tmpl = self._spec_tmpl
        if (tmpl is not None and cfg.submit_plane_native_enabled
                and self._spec_tmpl_key == (w.worker_id, id(cfg))):
            spec = build_spec_from_template(
                tmpl, TaskID.from_random(), args_blob, _current_trace_ctx())
            num_returns = tmpl.num_returns
        else:
            fn_id = self._ensure_registered(w)
            o = self._opts
            resources = dict(o.get("resources") or ())
            resources["CPU"] = float(o.get("num_cpus", 1))
            if o.get("num_tpus"):
                resources["TPU"] = float(o["num_tpus"])
            if o.get("num_gpus"):
                resources["GPU"] = float(o["num_gpus"])
            if o.get("memory"):
                resources["memory"] = float(o["memory"])
            strategy = o.get("scheduling_strategy", "DEFAULT")
            strategy = resolve_pg_strategy(strategy)
            if o.get("runtime_env"):
                from . import runtime_env as _renv
                _renv.validate(o["runtime_env"])
            num_returns = o.get("num_returns", 1)
            if num_returns in ("streaming", "dynamic"):
                num_returns = STREAMING_RETURNS
            spec = TaskSpec(
                task_id=TaskID.from_random(),
                job_id=w.job_id,
                name=o.get("name") or self.__name__,
                fn_id=fn_id,
                args=args_blob,
                num_returns=num_returns,
                resources=resources,
                owner=w.address,
                scheduling_strategy=strategy,
                max_retries=o.get("max_retries", cfg.default_task_max_retries),
                retry_exceptions=bool(o.get("retry_exceptions", False)),
                runtime_env=o.get("runtime_env"),
                generator_backpressure=int(o.get("generator_backpressure", 0)),
                trace_ctx=_current_trace_ctx(),
            )
            # Cache the template OUTSIDE the free list (never recycled,
            # never submitted — it only ever sources slot copies).  PG
            # strategies stay on the cold path: their bundle placement
            # resolves per call and must not be frozen into a template.
            if (cfg.submit_plane_native_enabled
                    and not isinstance(o.get("scheduling_strategy"),
                                       PlacementGroupSchedulingStrategy)):
                tmpl = TaskSpec.__new__(TaskSpec)
                copy_spec_into(spec, tmpl)
                self._spec_tmpl = tmpl
                self._spec_tmpl_key = (w.worker_id, id(cfg))
        refs = w.submit_task(spec, arg_refs)
        if num_returns == STREAMING_RETURNS:
            return refs  # an ObjectRefGenerator
        if num_returns == 0:
            return None
        return refs[0] if num_returns == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(f"Remote function '{self.__name__}' cannot be called directly. "
                        f"Use '{self.__name__}.remote()'.")


def resolve_pg_strategy(strategy):
    """Resolve a PlacementGroupSchedulingStrategy to a bundle-pinned node affinity
    (the PG manager placed bundles on concrete nodes at creation)."""
    if not isinstance(strategy, PlacementGroupSchedulingStrategy):
        return strategy
    pg = strategy.placement_group
    idx = strategy.placement_group_bundle_index
    if idx < 0:
        idx = 0
    placement = pg.bundle_placement()
    node_id, _addr = placement[idx]
    return ("_pg", pg.id, idx, node_id)
