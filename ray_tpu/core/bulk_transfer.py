"""Bulk transfer channel: threaded blocking-socket chunk movement.

The asyncio RPC path tops out far below the wire on chunked pulls: every
payload byte funnels through ONE event-loop thread per process and is
copied twice in user space on the way (transport read -> StreamReader
buffer -> sink; measured ~0.4 GB/s per agent, flat no matter how many
sockets).  This module is the data plane's side channel — the same split
the reference runs (``object_manager.cc`` drives its chunk reads/writes
on ``rpc_service_`` THREADS, not the raylet's main loop):

* Each node agent runs a :class:`BulkServer`: a listening socket whose
  per-connection handler THREADS serve ``read_chunk`` requests with
  ``sendall(memoryview-over-shm)`` — one kernel crossing, zero user-space
  copies, GIL released for the whole send.  Entry/proxy records are
  PINNED around the send (marshalled onto the agent loop), so eviction
  and owner frees defer exactly like they do for zero-copy readers.
* The puller side (:class:`BulkPool`) keeps ``transfer_sockets_per_source``
  persistent blocking sockets per source and lands each chunk with
  ``recv_into`` STRAIGHT into the destination shm segment from an
  executor thread — kernel -> arena, no intermediate buffer, GIL
  released, landings from different sources running on different cores.

Protocol (one in-flight request per socket, strictly sequential):

    request:  MAGIC(2s) | flags(u8, bit0 = crc) | oid_len(u8) | oid |
              offset(u64) | length(u64)
    reply:    status(u8) | crc(u32) | algo_len(u8) | algo | nbytes(u64) |
              payload
    status:   0 = ok, 1 = range not available (typed ChunkNotAvailable),
              2 = error (utf-8 message as payload)

Fault injection parity: the client consults the chaos injector for the
``read_chunk`` method (delay / drop_request / drop_reply / partition), so
seeded chaos schedules exercise this channel exactly like the RPC one.
The asyncio ``read_chunk`` RPC remains the fallback (unknown bulk port,
``transfer_sockets_per_source=1`` — the A/B off arm) and the only path
for agent-less drivers.
"""

from __future__ import annotations

import asyncio
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from . import chaos
from .ids import ObjectID
from .object_store import ChunkNotAvailable

MAGIC = b"rB"
_REQ_FIX = struct.Struct("!2sBB")       # magic, flags, oid_len
_REQ_RANGE = struct.Struct("!QQ")       # offset, length
_REP_FIX = struct.Struct("!BIB")        # status, crc, algo_len

#: socket buffer caps (not committed memory) for the bulk sockets
SOCK_BUF = 8 << 20
#: recv_into slice cap per syscall (bounds per-call latency without
#: bounding throughput)
RECV_SLICE = 4 << 20

ST_OK, ST_NOT_AVAILABLE, ST_ERROR = 0, 1, 2


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    pos, n = 0, view.nbytes
    while pos < n:
        got = sock.recv_into(view[pos:pos + min(RECV_SLICE, n - pos)])
        if got == 0:
            raise ConnectionError("bulk peer closed mid-reply")
        pos += got


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return bytes(buf)


# ------------------------------------------------------------------ server

class BulkServer:
    """Per-agent threaded chunk server.

    ``acquire``/``release`` are coroutines the OWNING AGENT provides;
    they run on the agent's event loop (the store is loop-confined) and
    bracket serving with a pin, so the view a serving thread is pushing
    into the kernel can never have its arena range recycled under it.
    ``acquire(oid, off, n) -> (view, kind, full)``: with ``full`` the
    view spans the WHOLE sealed object and the connection CACHES the
    pinned grant — subsequent chunks of the same object slice it without
    another loop round trip (the per-chunk marshal was the serving
    ceiling: two cross-thread hops per 2 MB chunk put the agent loop
    back in the middle of every byte).  Partial holders return
    ``full=False`` per-chunk grants.  Cached grants age out after
    :data:`GRANT_TTL_S` (bounding how long a deferred free can stay
    servable) and are released on idle/replacement/close."""

    #: cached full-object grant lifetime; re-acquired after (bounds the
    #: window in which a freed-deferred object could still be served)
    GRANT_TTL_S = 5.0
    #: per-connection grant cache size (a pull streams one object; a few
    #: interleaved objects per stripe is already unusual)
    GRANT_CACHE_MAX = 4

    def __init__(self, acquire, release, loop: asyncio.AbstractEventLoop,
                 host: str = "127.0.0.1", on_sent=None):
        self._acquire = acquire
        self._release = release
        self._loop = loop
        #: optional per-send accounting hook ``(nbytes) -> None``, called
        #: from serving threads (must be thread-safe)
        self._on_sent = on_sent
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._closed = False
        self._conns: set = set()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="bulk-accept", daemon=True)
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _peer = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, SOCK_BUF)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             name="bulk-serve", daemon=True).start()

    def _release_grant(self, oid: ObjectID, kind):
        try:
            asyncio.run_coroutine_threadsafe(self._release(oid, kind),
                                             self._loop)
        except RuntimeError:
            pass  # loop already closed (agent shutdown)

    def _serve(self, conn: socket.socket):
        grants: dict = {}   # oid -> (full_view, kind, t_acquired)
        conn.settimeout(10.0)
        try:
            while not self._closed:
                try:
                    fix = _recv_exact(conn, _REQ_FIX.size)
                except socket.timeout:
                    # idle: drop cached grants so pins don't outlive use
                    for o, (_v, kind, _t) in grants.items():
                        self._release_grant(o, kind)
                    grants.clear()
                    continue
                except (ConnectionError, OSError):
                    return
                magic, flags, oid_len = _REQ_FIX.unpack(fix)
                if magic != MAGIC:
                    return  # not our protocol: drop the connection
                oid = ObjectID(_recv_exact(conn, oid_len))
                off, length = _REQ_RANGE.unpack(
                    _recv_exact(conn, _REQ_RANGE.size))
                self._serve_one(conn, grants, oid, off, length,
                                bool(flags & 1))
        except (ConnectionError, OSError):
            pass
        finally:
            for o, (_v, kind, _t) in grants.items():
                self._release_grant(o, kind)
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _grant_for(self, grants: dict, oid: ObjectID, off: int,
                   length: int):
        """-> (view_of_chunk, release_kind | None).  A None kind means
        the grant is cached — no release after this send."""
        hit = grants.get(oid)
        if hit is not None:
            view, kind, t0 = hit
            if time.monotonic() - t0 <= self.GRANT_TTL_S:
                if off + length <= view.nbytes:
                    return view[off:off + length], None
            del grants[oid]
            self._release_grant(oid, kind)
        view, kind, full = asyncio.run_coroutine_threadsafe(
            self._acquire(oid, off, length), self._loop).result(30.0)
        if not full:
            return view, kind
        while len(grants) >= self.GRANT_CACHE_MAX:
            old_oid, (_v, old_kind, _t) = next(iter(grants.items()))
            del grants[old_oid]
            self._release_grant(old_oid, old_kind)
        grants[oid] = (view, kind, time.monotonic())
        if off + length > view.nbytes:
            raise ChunkNotAvailable(
                f"[{off}, {off + length}) outside object of "
                f"{view.nbytes} B")
        return view[off:off + length], None

    def _serve_one(self, conn, grants: dict, oid: ObjectID, off: int,
                   length: int, with_crc: bool):
        """Serve one chunk: pinned view granted on the agent loop (cached
        per connection for sealed objects), sendall from this thread
        (GIL released)."""
        kind = None
        try:
            view, kind = self._grant_for(grants, oid, off, length)
        except ChunkNotAvailable:
            conn.sendall(_REP_FIX.pack(ST_NOT_AVAILABLE, 0, 0)
                         + struct.pack("!Q", 0))
            return
        except Exception as e:  # noqa: BLE001 — typed error reply
            msg = f"{type(e).__name__}: {e}".encode()[:4096]
            conn.sendall(_REP_FIX.pack(ST_ERROR, 0, 0)
                         + struct.pack("!Q", len(msg)) + msg)
            return
        try:
            crc, algo = 0, b""
            if with_crc:
                from .transfer import chunk_checksum
                crc_v, algo_s = chunk_checksum(view)
                crc, algo = crc_v & 0xFFFFFFFF, algo_s.encode()
            header = (_REP_FIX.pack(ST_OK, crc, len(algo)) + algo
                      + struct.pack("!Q", view.nbytes))
            conn.sendall(header)
            conn.sendall(view)  # memoryview straight over the pinned shm
            if self._on_sent is not None:
                try:
                    self._on_sent(view.nbytes)
                except Exception:
                    pass
        finally:
            if kind is not None:
                self._release_grant(oid, kind)

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        for conn in list(self._conns):
            try:
                conn.close()
            except OSError:
                pass


# ------------------------------------------------------------------ client

class BulkPool:
    """Per-process cache of blocking bulk sockets, keyed by
    ``(bulk address, stripe)`` — one lock per socket (the protocol is
    strictly sequential per connection), stripes giving a source
    ``transfer_sockets_per_source`` parallel streams.

    ``fetch`` BLOCKS (run it in an executor thread): it sends one
    request and lands the reply with ``recv_into`` straight into the
    caller's sink view — both directions release the GIL, so concurrent
    fetches from different sources run on different cores."""

    def __init__(self):
        self._socks: Dict[Tuple[str, int], Tuple[socket.socket,
                                                 threading.Lock]] = {}
        self._map_lock = threading.Lock()

    def _get(self, bulk_addr: str, stripe: int, timeout: float):
        key = (bulk_addr, stripe)
        with self._map_lock:
            ent = self._socks.get(key)
            if ent is None:
                ent = (None, threading.Lock())
                self._socks[key] = ent
        sock, lock = ent
        if sock is not None:
            return sock, lock
        with lock:  # serialize the connect per key
            with self._map_lock:
                cur = self._socks.get(key)
            if cur is not None and cur[0] is not None:
                return cur
            host, port = bulk_addr.rsplit(":", 1)
            sock = socket.create_connection(
                (host, int(port)), timeout=min(10.0, timeout))
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, SOCK_BUF)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._map_lock:
                self._socks[key] = (sock, lock)
            return sock, lock

    def drop_stripe(self, bulk_addr: str, stripe: int):
        """Kill ONE stripe's socket — an in-flight landing on it fails
        within a syscall; other stripes to the same address are
        untouched."""
        with self._map_lock:
            ent = self._socks.pop((bulk_addr, stripe), None)
        if ent and ent[0] is not None:
            try:
                ent[0].close()
            except OSError:
                pass

    def drop_addr(self, bulk_addr: str):
        """Kill every stripe to one address — forces any in-flight
        landing on it to fail fast (the no-late-write teardown path)."""
        with self._map_lock:
            keys = [k for k in self._socks if k[0] == bulk_addr]
        for key in keys:
            self.drop_stripe(*key)

    def fetch(self, rpc_addr: str, bulk_addr: str, stripe: int,
              oid: ObjectID, off: int, length: int, sink: memoryview,
              with_crc: bool, timeout: float) -> int:
        """Land ``[off, off+length)`` of ``oid`` into ``sink``; returns
        bytes landed.  ``rpc_addr`` is the source's RPC address — the
        chaos injector keys links by it, so seeded fault schedules hit
        this channel exactly like the RPC one."""
        inj = chaos.injector()
        if inj is not None:
            if inj.should("partition", "read_chunk", rpc_addr):
                raise ConnectionError(
                    f"chaos: link to {rpc_addr} partitioned")
            d = inj.delay_s("read_chunk", rpc_addr)
            if d > 0:
                time.sleep(d)
            if inj.should("drop_request", "read_chunk", rpc_addr):
                self.drop_stripe(bulk_addr, stripe)
                raise ConnectionError("chaos: bulk request dropped")
        sock, lock = self._get(bulk_addr, stripe, timeout)
        oid_b = oid.binary()
        req = (_REQ_FIX.pack(MAGIC, 1 if with_crc else 0, len(oid_b))
               + oid_b + _REQ_RANGE.pack(off, length))
        with lock:
            sock.settimeout(timeout)
            try:
                sock.sendall(req)
                fix = _recv_exact(sock, _REP_FIX.size)
                status, crc, algo_len = _REP_FIX.unpack(fix)
                algo = _recv_exact(sock, algo_len).decode() if algo_len \
                    else ""
                (nbytes,) = struct.unpack("!Q", _recv_exact(sock, 8))
                if status == ST_NOT_AVAILABLE:
                    raise ChunkNotAvailable(
                        f"{rpc_addr}: [{off}, {off + length}) not held")
                if status != ST_OK:
                    msg = _recv_exact(sock, nbytes).decode(
                        errors="replace") if nbytes else "bulk error"
                    raise RuntimeError(f"bulk read_chunk at {rpc_addr}: "
                                       f"{msg}")
                if nbytes > sink.nbytes:
                    raise ConnectionError(
                        f"bulk reply {nbytes} B exceeds sink "
                        f"{sink.nbytes} B")
                if with_crc:
                    # verify-then-copy through a scratch buffer: a
                    # work-steal straggler must never overwrite a DONE
                    # chunk's bytes with an unverified reply
                    scratch = bytearray(nbytes)
                    _recv_exact_into(sock, memoryview(scratch))
                    from .transfer import ChunkCrcError, chunk_checksum
                    got, got_algo = chunk_checksum(scratch)
                    if algo and got_algo == algo \
                            and (got & 0xFFFFFFFF) != crc:
                        raise ChunkCrcError(
                            f"bulk chunk [{off}, {off + nbytes}) from "
                            f"{rpc_addr}: checksum mismatch")
                    sink[:nbytes] = scratch
                else:
                    _recv_exact_into(sock, sink[:nbytes])
                if inj is not None and inj.should("drop_reply",
                                                  "read_chunk", rpc_addr):
                    # the bytes landed, but the caller must observe a
                    # dead link (reply "lost"): drop the socket and fail
                    self.drop_stripe(bulk_addr, stripe)
                    raise ConnectionError("chaos: bulk reply dropped")
                return nbytes
            except (socket.timeout, TimeoutError) as e:
                # a timed-out socket is mid-stream garbage: drop it
                self.drop_stripe(bulk_addr, stripe)
                raise asyncio.TimeoutError(
                    f"bulk read_chunk to {rpc_addr} timed out") from e
            except (ConnectionError, OSError):
                self.drop_stripe(bulk_addr, stripe)
                raise

    def close(self):
        with self._map_lock:
            socks = list(self._socks.values())
            self._socks.clear()
        for sock, _lock in socks:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
