"""Chunk-ledger transfer plane: pipelined multi-source object pulls.

The cross-host byte path of a broadcast (reference: ``push_manager.h``
chunked parallel push + ``pull_manager.h`` admission control) rebuilt as a
pull-side **chunk ledger**:

* **Multi-source striping** — the chunks of ONE object are scheduled across
  every known source concurrently (per-source in-flight windows under one
  global per-pull window), instead of a whole-object pull from a single
  randomly chosen candidate.
* **Work stealing** — a source with no claimable pending chunk hedges the
  slowest in-flight chunk of another source (duplicate fetch; both land the
  same bytes at the same offset, the first completion wins the ledger).
* **Partial-object serving** — every landed chunk is published to the local
  store as a sealed *range*, so this puller becomes a source after one
  chunk-time, not one object-time; an N-node broadcast forms a pipeline.
* **Mid-pull source refresh** — the owner's location view is re-polled
  while the pull is in flight and newly registered (possibly partial)
  sources are folded into the stripe.
* **Chunk-granular failure handling** — a failed/short/corrupt chunk goes
  back to PENDING and is retried on another source against the ledger; a
  source is dropped only after repeated failures, and the pull survives any
  strict subset of its sources dying.

The engine is transport-agnostic (callbacks for fetch/probe/refresh) so the
striping, stealing and resume logic unit-test without a cluster; the node
agent supplies RPC-backed callbacks (see ``NodeAgent._pull_object``).

Sources are opaque ADDRESS strings to the engine — the agent's callbacks
route ``host:port`` addresses over RPC and **external-tier URIs**
(``gs://...``, ``file://...`` — see ``core/external_spill.py``) through
fsspec range reads, so an object spilled to the external tier by a node
that later died participates in the stripe exactly like a live peer:
claimable chunk-by-chunk, hedgeable, retried, folded in by the mid-pull
owner refresh when its registration lands mid-broadcast.
"""

from __future__ import annotations

import asyncio
import bisect
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional

from .object_store import ChunkNotAvailable, range_covers

PENDING, INFLIGHT, DONE = 0, 1, 2


class ChunkShortError(RuntimeError):
    """A ``read_chunk`` reply carried fewer (or more) bytes than requested —
    slice-assigning it silently would seal a corrupt object."""


class ChunkCrcError(RuntimeError):
    """Optional per-chunk checksum mismatch (object_transfer_checksum)."""


class TransferStalled(RuntimeError):
    """No chunk landed within the stall window and no live source remains."""


# ------------------------------------------------------------- self-metrics

def _build_transfer_metrics():
    from ray_tpu.util.metrics import Counter, Histogram
    return {
        "bytes": Counter(
            "raytpu_transfer_bytes_total",
            "object-plane payload bytes moved, by kind and direction",
            tag_keys=("kind", "direction")),
        "chunk_seconds": Histogram(
            "raytpu_transfer_chunk_seconds",
            "per-chunk transfer latency (request sent -> bytes landed)",
            boundaries=[0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                        1.0, 2.5, 5.0, 10.0, 30.0],
            tag_keys=("status",)),
        "pull_sources": Histogram(
            "raytpu_transfer_pull_sources",
            "distinct sources a completed chunked pull drew bytes from",
            boundaries=[1, 2, 3, 4, 6, 8, 12, 16, 24, 32]),
    }


_transfer_metrics_get = None

# precomputed sorted tag-key tuples (see Counter.inc_key): the chunk path
# runs per 8 MB of every cross-host transfer
KEY_CHUNK_IN = (("direction", "in"), ("kind", "chunk"))
KEY_CHUNK_OUT = (("direction", "out"), ("kind", "chunk"))
KEY_PROXY_IN = (("direction", "in"), ("kind", "proxy"))
KEY_OK = (("status", "ok"),)
KEY_FAIL = (("status", "failed"),)


def transfer_metrics():
    global _transfer_metrics_get
    if _transfer_metrics_get is None:
        from ray_tpu.util.metrics import lazy
        _transfer_metrics_get = lazy(_build_transfer_metrics)
    return _transfer_metrics_get()


# ------------------------------------------------------------- chunk ledger

class ChunkLedger:
    """Per-pull bookkeeping: which byte ranges are PENDING / INFLIGHT / DONE,
    who is fetching what, and the counters the timeline artifact reports."""

    def __init__(self, size: int, chunk_bytes: int,
                 order: Optional[List[int]] = None):
        self.size = size
        self.chunk_bytes = max(1, int(chunk_bytes))
        self.offsets = list(range(0, size, self.chunk_bytes)) or [0]
        n = len(self.offsets)
        #: claim scan order.  Pullers in one broadcast should each use a
        #: DIFFERENT permutation (rarest-first in spirit): with everyone
        #: claiming 0..N in lockstep, peers only ever hold the prefix the
        #: others already landed and partial serving relays nothing —
        #: permuted orders make peers' ranges complementary.
        self.order = list(order) if order is not None else list(range(n))
        self.state = [PENDING] * n
        self.assigned: List[Optional[str]] = [None] * n
        self.started = [0.0] * n
        self.fetchers = [0] * n          # concurrent attempts (steal hedges)
        self.done_n = 0
        self.retries = 0                 # chunk attempts that failed
        self.steals = 0                  # hedged duplicate fetches issued
        self.short_chunks = 0            # length-mismatch replies rejected
        self.chunk_times: List[float] = []

    def __len__(self) -> int:
        return len(self.offsets)

    def chunk_len(self, i: int) -> int:
        return min(self.chunk_bytes, self.size - self.offsets[i])

    @property
    def done(self) -> bool:
        return self.done_n == len(self.offsets)

    def sealed_ranges(self) -> List[List[int]]:
        """Merged [start, end) byte ranges of DONE chunks (what object_info
        advertises while this pull is still in flight)."""
        out: List[List[int]] = []
        for i, st in enumerate(self.state):
            if st != DONE:
                continue
            s, e = self.offsets[i], self.offsets[i] + self.chunk_len(i)
            if out and out[-1][1] == s:
                out[-1][1] = e
            else:
                out.append([s, e])
        return out

    def claim(self, source: str, covered: Callable[[int, int], bool],
              rank: Optional[Callable[[int], int]] = None) -> Optional[int]:
        """Next PENDING chunk (in this ledger's claim order) this source
        can serve; marks it INFLIGHT.

        ``rank`` (lower = claim first) implements rarest-first proper: the
        engine ranks each chunk by how many OTHER live sources could serve
        it, so a full source (the origin) works the chunks only it holds
        and leaves commonly-held ranges to the relays — raising the relay
        fraction AND taking load off the origin."""
        best = best_rank = None
        examined = 0
        for i in self.order:
            if self.state[i] != PENDING:
                continue
            if covered(self.offsets[i], self.chunk_len(i)):
                if rank is None:
                    best = i
                    break
                r = rank(i)
                if best_rank is None or r < best_rank:
                    best, best_rank = i, r
                    if r == 0:
                        break  # nobody else can serve it: claim now
                examined += 1
                if examined >= 64:
                    break  # cap the scan: huge pulls stay O(64 x sources)
        if best is None:
            return None
        self.state[best] = INFLIGHT
        self.assigned[best] = source
        self.started[best] = time.monotonic()
        self.fetchers[best] += 1
        return best

    def claim_run(self, source: str, covered: Callable[[int, int], bool],
                  rank: Optional[Callable[[int], int]] = None,
                  max_chunks: int = 1) -> Optional[List[int]]:
        """Claim a RUN: :meth:`claim`'s pick plus up to ``max_chunks - 1``
        offset-consecutive PENDING chunks the source also covers, all
        marked INFLIGHT as one fetch unit (one request on the wire).

        This is the adaptive chunk-growth substrate: the LEDGER keeps its
        fixed base-chunk bookkeeping (steal, retry, partial publishing
        stay chunk-granular), while the per-request size on the wire
        grows to the run — fewer round trips, same failure granularity:
        a failed run requeues per base chunk."""
        first = self.claim(source, covered, rank)
        if first is None or max_chunks <= 1:
            return None if first is None else [first]
        run = [first]
        t0 = self.started[first]
        # STAGGER the per-chunk start stamps across the run by the median
        # completed-chunk time: the steal clock compares per-chunk ages
        # against a per-base-chunk threshold, and a healthy 32-chunk run
        # stamped wholesale at t0 would look 32 chunk-times "slow" by its
        # tail — systematically hedged and its source shrunk for being
        # fast.  Staggered, chunk k of a run only ages once its expected
        # service time has actually passed.
        per = (self.chunk_times[len(self.chunk_times) // 2]
               if self.chunk_times else 0.25)
        i = first + 1
        n = len(self.offsets)
        while len(run) < max_chunks and i < n and self.state[i] == PENDING \
                and covered(self.offsets[i], self.chunk_len(i)):
            self.state[i] = INFLIGHT
            self.assigned[i] = source
            self.started[i] = t0 + len(run) * per
            self.fetchers[i] += 1
            run.append(i)
            i += 1
        return run

    def run_span(self, run: List[int]) -> tuple:
        """(offset, length) of one offset-consecutive claimed run."""
        off = self.offsets[run[0]]
        end = self.offsets[run[-1]] + self.chunk_len(run[-1])
        return off, end - off

    def complete_run(self, run: List[int], elapsed_s: float) -> bool:
        """Mark every chunk of a run DONE (per-chunk time = the run's
        mean).  True if ANY chunk was first-landed by this run."""
        per = elapsed_s / max(1, len(run))
        first = False
        for i in run:
            if self.complete(i, per):
                first = True
        return first

    def fail_run(self, run: List[int]):
        for i in run:
            self.fail(i)

    def steal(self, source: str, covered: Callable[[int, int], bool],
              threshold_s: float) -> Optional[int]:
        """Hedge the SLOWEST in-flight chunk another source has held longer
        than ``threshold_s`` (and that nobody hedges yet).  The duplicate
        fetch lands the same bytes at the same offset — first completion
        wins the ledger, the straggler's completion is a no-op."""
        now = time.monotonic()
        best, best_age = None, threshold_s
        for i, st in enumerate(self.state):
            if st != INFLIGHT or self.assigned[i] == source \
                    or self.fetchers[i] > 1:
                continue
            age = now - self.started[i]
            if age >= best_age and covered(self.offsets[i],
                                           self.chunk_len(i)):
                best, best_age = i, age
        if best is not None:
            self.fetchers[best] += 1
            self.steals += 1
        return best

    def steal_threshold(self, configured_s: float) -> float:
        """Fixed when configured > 0; otherwise adaptive — twice the median
        completed-chunk time, floored so a warm-up blip can't trigger a
        hedge storm.  ``chunk_times`` is kept sorted (insort on complete),
        so this is O(1) — idle slots poll it every cycle."""
        if configured_s > 0:
            return configured_s
        if not self.chunk_times:
            return 1.0
        med = self.chunk_times[len(self.chunk_times) // 2]
        return max(0.25, 2.0 * med)

    def complete(self, i: int, elapsed_s: float) -> bool:
        """Mark chunk ``i`` DONE.  False if a duplicate already landed it."""
        self.fetchers[i] = max(0, self.fetchers[i] - 1)
        if self.state[i] == DONE:
            return False
        self.state[i] = DONE
        self.done_n += 1
        bisect.insort(self.chunk_times, elapsed_s)
        return True

    def fail(self, i: int):
        """A fetch attempt died: requeue unless a duplicate already won."""
        self.fetchers[i] = max(0, self.fetchers[i] - 1)
        if self.state[i] == DONE:
            return
        self.retries += 1
        if self.fetchers[i] == 0:
            self.state[i] = PENDING
            self.assigned[i] = None

    def stats(self) -> dict:
        return {"chunks": len(self.offsets), "chunks_done": self.done_n,
                "retried": self.retries, "stolen": self.steals,
                "short": self.short_chunks}


# ---------------------------------------------------------- source tracking

@dataclass
class SourceState:
    addr: str
    #: None = assumed full object; else merged [start, end) ranges held
    ranges: Optional[List[List[int]]] = None
    inflight: int = 0
    #: CONSECUTIVE failure events (reset by any success): one aborted
    #: connection fails every windowed chunk on it at the same instant, so
    #: failures landing within ``FAIL_DEBOUNCE_S`` count as ONE event — a
    #: 5% frame-drop link survives, a dead host still dies in ~3 events
    failures: int = 0
    last_fail_t: float = 0.0
    dead: bool = False
    #: set after ChunkNotAvailable: don't re-claim against stale ranges
    #: until a re-probe refreshes them (event-driven when a prober
    #: exists — see StripedPull._probe_soon — else the refresh tick)
    wait_probe: bool = False
    #: monotonic time of the last issued probe (the event-driven probe's
    #: debounce clock) and whether one is currently in flight
    last_probe_t: float = 0.0
    probe_inflight: bool = False
    chunks: int = 0
    bytes: int = 0
    t_first: float = 0.0
    t_last: float = 0.0
    #: adaptive per-request size, in base chunks: grows geometrically
    #: under clean completions (see StripedPull._grow/_shrink), shrinks
    #: on failure/timeout and when another source steals this one's
    #: in-flight work (slowness evidence)
    run_len: int = 1
    clean: int = 0

    FAIL_DEBOUNCE_S = 0.1

    def covers(self, offset: int, length: int) -> bool:
        if self.ranges is None:
            return True
        return range_covers(self.ranges, offset, offset + length)

    def note_failure(self) -> int:
        now = time.monotonic()
        if now - self.last_fail_t > self.FAIL_DEBOUNCE_S:
            self.failures += 1
        self.last_fail_t = now
        return self.failures


# -------------------------------------------------------------- the engine

class StripedPull:
    """Drive one object pull across many sources against a ChunkLedger.

    Callbacks (all coroutines):

    * ``fetch_chunk(addr, offset, length)`` — land [offset, offset+length)
      from ``addr`` into the destination and return the byte count landed.
      Raise :class:`ChunkNotAvailable` when the source doesn't hold the
      range (partial holder), anything else for a transport/content fault.
    * ``probe_source(addr)`` — ``None`` (unusable now) or
      ``{"full": bool, "ranges": [[s, e), ...]}``.
    * ``refresh_sources()`` — current full location list from the owner
      (may include partial holders that registered mid-broadcast).
    * ``on_chunk(i, offset, length, addr, t0, t1, stolen)`` — optional sync
      hook per FIRST landing of a chunk (trace/metrics/partial publish).
    """

    def __init__(self, ledger: ChunkLedger, *,
                 fetch_chunk: Callable[[str, int, int], Awaitable[int]],
                 probe_source: Optional[Callable[
                     [str], Awaitable[Optional[dict]]]] = None,
                 refresh_sources: Optional[Callable[
                     [], Awaitable[List[str]]]] = None,
                 on_chunk: Optional[Callable] = None,
                 per_source_window: int = 4,
                 total_window: int = 16,
                 steal_after_s: float = 0.0,
                 max_source_failures: int = 3,
                 refresh_period_s: float = 0.5,
                 stall_timeout_s: float = 60.0,
                 run_max_chunks: int = 1,
                 clamp_run_chunks: Optional[Callable[[], int]] = None):
        self.ledger = ledger
        self._fetch_chunk = fetch_chunk
        self._probe_source = probe_source
        self._refresh_sources = refresh_sources
        self._on_chunk = on_chunk
        self.per_source_window = max(1, per_source_window)
        self._window = asyncio.Semaphore(max(1, total_window))
        self.steal_after_s = steal_after_s
        self.max_source_failures = max(1, max_source_failures)
        self.refresh_period_s = refresh_period_s
        self.stall_timeout_s = stall_timeout_s
        #: adaptive chunk growth: per-request runs of base chunks grow
        #: toward this many chunks under clean completions (1 = fixed
        #: chunks, the pre-adaptive behavior)
        self.run_max_chunks = max(1, run_max_chunks)
        #: receiver-side clamp, re-queried per claim: the largest run (in
        #: base chunks) the receiving arena can absorb — grown requests
        #: must never outgrow the receiver's largest free block, or a
        #: landing could force a spill mid-pull
        self._clamp_run_chunks = clamp_run_chunks
        self.sources: Dict[str, SourceState] = {}
        self._slots: List[asyncio.Task] = []
        #: ephemeral event-driven probe tasks (self-pruning; separate
        #: from _slots so fetch-slot bookkeeping stays bounded)
        self._probes: set = set()
        self._last_progress = time.monotonic()
        self._done = asyncio.Event()
        #: wakes idle slots when claimable work may exist (chunk requeued,
        #: ranges widened, new source) — idle slots park on this instead
        #: of busy-polling; the wait's timeout is the steal-age clock
        self._kick = asyncio.Event()
        self._fatal: Optional[BaseException] = None

    # -- source management -------------------------------------------------

    def add_source(self, addr: str) -> Optional[SourceState]:
        s = self.sources.get(addr)
        if s is not None:
            return s
        s = SourceState(addr)
        self.sources[addr] = s
        self._spawn_slots(s)
        return s

    def _spawn_slots(self, s: SourceState):
        for _ in range(self.per_source_window):
            self._slots.append(asyncio.ensure_future(self._slot(s)))

    def _resurrect(self, s: SourceState):
        """Last-resort second life: the stripe has NO live source but the
        owner still lists this one — a spurious death (burst of transient
        faults) must not strand the pull when the holder is reachable."""
        s.dead = False
        s.failures = 0
        # re-probe before claiming against stale state (only meaningful
        # when a prober exists — it is what clears wait_probe)
        s.wait_probe = self._probe_source is not None
        self._spawn_slots(s)

    def _live_sources(self) -> List[SourceState]:
        return [s for s in self.sources.values() if not s.dead]

    # -- slots -------------------------------------------------------------

    def _coverage_rank(self, s: SourceState):
        """rank(i) = how many OTHER live sources could serve chunk i (the
        rarest-first claim key).  None when ranking cannot change the
        outcome — no other live source, or every other source is full
        (rank would be a constant) — so the common all-full case (one big
        pull from N complete holders) keeps O(1) claims instead of
        scanning every pending chunk per claim."""
        others = [o for o in self.sources.values()
                  if o is not s and not o.dead and not o.wait_probe]
        if not others or all(o.ranges is None for o in others):
            return None
        ledger = self.ledger

        def rank(i: int) -> int:
            off, ln = ledger.offsets[i], ledger.chunk_len(i)
            return sum(1 for o in others if o.covers(off, ln))

        return rank

    def _run_budget(self, s: SourceState) -> int:
        """Chunks this source's next claim may take: its adaptive run
        length, bounded by the engine max and the receiver-side clamp."""
        n = min(s.run_len, self.run_max_chunks)
        if self._clamp_run_chunks is not None:
            try:
                n = min(n, self._clamp_run_chunks())
            except Exception:
                n = 1
        return max(1, n)

    def _grow(self, s: SourceState):
        s.clean += 1
        if s.clean >= 2 and s.run_len < self.run_max_chunks:
            s.run_len = min(self.run_max_chunks, s.run_len * 2)
            s.clean = 0

    def _shrink(self, s: SourceState):
        s.clean = 0
        s.run_len = max(1, s.run_len // 2)

    async def _slot(self, s: SourceState):
        ledger = self.ledger
        while not ledger.done and not s.dead and self._fatal is None:
            worked = False
            async with self._window:
                run = None
                stolen = False
                if not s.wait_probe:
                    run = ledger.claim_run(s.addr, s.covers,
                                           self._coverage_rank(s),
                                           self._run_budget(s))
                    if run is None:
                        i = ledger.steal(
                            s.addr, s.covers,
                            ledger.steal_threshold(self.steal_after_s))
                        if i is not None:
                            run, stolen = [i], True
                            # slowness evidence against the victim: its
                            # next requests should shrink, not grow
                            victim = self.sources.get(ledger.assigned[i])
                            if victim is not None and victim is not s:
                                self._shrink(victim)
                if run is not None:
                    worked = True
                    await self._fetch_one(s, run, stolen)
            if ledger.done:
                break
            if not worked:
                # nothing claimable right now (all pending chunks outside
                # this source's ranges, or everything in flight): park on
                # the kick event — requeues/range-widening/new-source wake
                # us; the timeout is only the steal-age clock (hedging
                # needs time to pass, not an event)
                self._kick.clear()
                try:
                    await asyncio.wait_for(self._kick.wait(), 0.2)
                except asyncio.TimeoutError:
                    pass
        if ledger.done:
            self._done.set()

    async def _fetch_one(self, s: SourceState, run: List[int],
                         stolen: bool):
        ledger = self.ledger
        off, n = ledger.run_span(run)
        t0 = time.time()
        tm0 = time.monotonic()
        s.inflight += 1
        m = transfer_metrics()
        try:
            landed = await self._fetch_chunk(s.addr, off, n)
            if landed != n:
                ledger.short_chunks += 1
                raise ChunkShortError(
                    f"source {s.addr} returned {landed} B for a {n} B chunk "
                    f"at offset {off}")
        except ChunkNotAvailable:
            # partial holder that doesn't (yet) cover this range: requeue
            # the chunks and — when a prober exists to clear the flag —
            # stop claiming against its stale range map until a re-probe
            # widens it.  The re-probe is EVENT-DRIVEN (debounced), not
            # left to the refresh tick: in a fast broadcast a relay's
            # ranges widen every few chunk-times, and a tick-period pause
            # would idle the relay for most of the transfer (without a
            # prober the pause would be permanent, so just back off
            # briefly instead).
            if self._probe_source is not None:
                s.wait_probe = True
                self._probe_soon(s)
            ledger.fail_run(run)
            self._kick.set()  # the requeued chunks are claimable elsewhere
            await asyncio.sleep(0.01)
        except asyncio.CancelledError:
            ledger.fail_run(run)
            raise
        except BaseException:
            ledger.fail_run(run)
            self._shrink(s)  # timeout/transport fault: smaller requests
            if m is not None:
                m["chunk_seconds"].observe_key(KEY_FAIL,
                                               time.monotonic() - tm0)
            if s.note_failure() >= self.max_source_failures:
                s.dead = True
            self._kick.set()  # the requeued chunks are claimable elsewhere
            # brief backoff so a fast-failing source can't hot-spin the
            # claim/fail cycle on the event loop
            await asyncio.sleep(0.01)
        else:
            elapsed = time.monotonic() - tm0
            s.failures = 0  # consecutive-failure semantics
            first = ledger.complete_run(run, elapsed)
            self._grow(s)
            if first:
                self._last_progress = time.monotonic()
                s.chunks += len(run)
                s.bytes += n
                if not s.t_first:
                    s.t_first = t0
                s.t_last = time.time()
                if m is not None:
                    m["bytes"].inc_key(KEY_CHUNK_IN, n)
                    m["chunk_seconds"].observe_key(KEY_OK, elapsed)
                if self._on_chunk is not None:
                    try:
                        self._on_chunk(run[0], off, n, s.addr, t0,
                                       time.time(), stolen)
                    except Exception:
                        pass
            if ledger.done:
                self._done.set()
        finally:
            s.inflight -= 1

    #: event-driven probe debounce: a paused source is re-probed at most
    #: this often (a relay lands ~one chunk per chunk-time; probing much
    #: faster than that only burns RPCs)
    PROBE_DEBOUNCE_S = 0.05

    def _probe_soon(self, s: SourceState):
        """Schedule one debounced probe of a paused (wait_probe) source so
        its range map widens at chunk-time granularity instead of
        refresh-tick granularity."""
        if self._probe_source is None or s.probe_inflight or s.dead:
            return
        s.probe_inflight = True

        async def _go():
            try:
                delay = (s.last_probe_t + self.PROBE_DEBOUNCE_S
                         - time.monotonic())
                if delay > 0:
                    await asyncio.sleep(delay)
                s.last_probe_t = time.monotonic()
                try:
                    info = await self._probe_source(s.addr)
                except Exception:
                    info = None
                if info is not None:
                    s.ranges = (None if info.get("full")
                                else [list(r) for r in
                                      info.get("ranges", [])])
                    s.wait_probe = False
                    self._kick.set()  # widened ranges: wake idle slots
            finally:
                s.probe_inflight = False

        t = asyncio.ensure_future(_go())
        self._probes.add(t)
        t.add_done_callback(self._probes.discard)

    # -- refresh / stall watchdog ------------------------------------------

    async def _refresh_loop(self):
        empty_rounds = 0
        while not self.ledger.done and self._fatal is None:
            await asyncio.sleep(self.refresh_period_s)
            if self.ledger.done:
                break
            # fold newly registered sources into the stripe
            if self._refresh_sources is not None:
                try:
                    addrs = await self._refresh_sources()
                except Exception:
                    addrs = []
                for addr in addrs:
                    s = self.sources.get(addr)
                    if s is None:
                        s = self.add_source(addr)
                        # a mid-pull source is usually a PARTIAL holder:
                        # probe it this tick (below) before it claims
                        # against an assumed-full range map
                        if self._probe_source is not None:
                            s.wait_probe = True
                    elif s.dead and not self._live_sources():
                        self._resurrect(s)
            # re-probe partial / paused sources so their range maps grow —
            # CONCURRENTLY: one hung peer must not stall every other
            # source's refresh (or the watchdog) for its probe timeout
            if self._probe_source is not None:
                targets = [s for s in self.sources.values()
                           if not s.dead
                           and not (s.ranges is None and not s.wait_probe)]

                async def _probe_one(s):
                    try:
                        return s, await self._probe_source(s.addr)
                    except Exception:
                        return s, None

                for s, info in await asyncio.gather(
                        *(_probe_one(s) for s in targets)):
                    if info is None:
                        s.wait_probe = True
                        continue
                    s.ranges = (None if info.get("full")
                                else [list(r) for r in
                                      info.get("ranges", [])])
                    s.wait_probe = False
            # sources added/resurrected or ranges widened: wake idle slots
            self._kick.set()
            live = self._live_sources()
            stalled_s = time.monotonic() - self._last_progress
            if not live:
                empty_rounds += 1
            else:
                empty_rounds = 0
            if (not live and empty_rounds >= 3
                    and (self._refresh_sources is None or stalled_s > 5.0)) \
                    or stalled_s > self.stall_timeout_s:
                self._fatal = TransferStalled(
                    f"pull stalled: {self.ledger.done_n}/{len(self.ledger)} "
                    f"chunks after {stalled_s:.1f}s, "
                    f"{len(live)} live sources")
                self._done.set()
                return

    # -- run ---------------------------------------------------------------

    async def run(self, initial_sources: List[str]) -> dict:
        """Pull until the ledger is complete.  Returns per-source stats.
        Raises the first fatal error (stall / cancellation) after all slot
        tasks have been torn down — the caller may then free the
        destination segment safely (no fetch can land into it afterwards)."""
        for addr in initial_sources:
            self.add_source(addr)
        if not self.sources and self._refresh_sources is None:
            raise TransferStalled("no sources to pull from")
        refresher = asyncio.ensure_future(self._refresh_loop())
        try:
            await self._done.wait()
        finally:
            refresher.cancel()
            probes = list(self._probes)  # snapshot: done-callbacks mutate
            for t in probes + self._slots:
                t.cancel()
            await asyncio.gather(refresher, *probes, *self._slots,
                                 return_exceptions=True)
        if self._fatal is not None and not self.ledger.done:
            raise self._fatal
        used = [s for s in self.sources.values() if s.chunks > 0]
        m = transfer_metrics()
        if m is not None:
            m["pull_sources"].observe(len(used))
        total_b = sum(s.bytes for s in used) or 0
        relay_b = sum(s.bytes for s in used if s.ranges is not None)
        return {
            "sources_used": sorted(s.addr for s in used),
            "per_source": {
                s.addr: {"chunks": s.chunks, "bytes": s.bytes,
                         "failures": s.failures, "dead": s.dead,
                         # partial holder = a relay of the broadcast (it
                         # advertised ranges, not a full copy)
                         "partial": s.ranges is not None}
                for s in self.sources.values()},
            # fraction of chunk bytes served by partial (relay) holders —
            # the pipeline-efficiency number the broadcast bench reports
            # offline, now on every pull record
            "relay_fraction": round(relay_b / total_b, 4) if total_b else 0.0,
            **self.ledger.stats(),
        }


# ------------------------------------------------------------ chunk checksum

def chunk_checksum(buf) -> tuple:
    """(crc, algo) over a chunk — native CRC-32C when the extension builds
    (``test_native_crc`` covers the primitive), zlib.crc32 otherwise.  Both
    ends compare algos before comparing sums, so a mixed deployment (one
    side without g++) degrades to skip, never to a false mismatch."""
    try:
        from ray_tpu.native import load_crc32c
        fn = load_crc32c()
    except Exception:
        fn = None
    if fn is not None:
        try:
            return fn(buf), "crc32c"
        except Exception:
            pass
    import zlib
    return zlib.crc32(buf) & 0xFFFFFFFF, "zlib"
