"""Cluster-level scheduling policies over the gossiped resource view.

Re-implements the behavior of the reference's pluggable policy set
(``src/ray/raylet/scheduling/policy/``):

* :func:`hybrid_policy` — the default (``hybrid_scheduling_policy.h:51``, doc comment
  :29-49): prefer the local node while its critical-resource utilization is below the
  spread threshold, then pick among the top-k least-utilized feasible nodes, breaking
  ties randomly to avoid herding.
* :func:`spread_policy` — round-robin over feasible nodes (``spread_scheduling_policy.h``).
* node-affinity / node-label / placement-group strategies are resolved before the
  policies run (reference: ``affinity_with_bundle_scheduling_policy.h``).

The *node view* is ``{node_id_hex: NodeView}`` maintained from GCS resource broadcasts
(reference analogue: RaySyncer gossip feeding ClusterResourceManager).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .common import (NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy,
                     PlacementGroupSchedulingStrategy)
from .config import get_config


@dataclass
class NodeView:
    node_id: str              # hex
    address: str              # agent rpc address
    total: Dict[str, float]
    available: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    queue_len: int = 0
    #: preemption notice received — still alive (finishing leases, spilling
    #: objects) but not schedulable: pick_node/pack_bundles skip it
    draining: bool = False
    #: resources currently held by short-lived TASK leases (non-actor,
    #: non-bundle) — capacity that returns to the pool within an idle-return
    #: window.  Elastic sizing counts it as reclaimable headroom: a node
    #: churning 1-CPU dataset tasks is not "full" to a worker-group probe.
    task_leased: Dict[str, float] = field(default_factory=dict)

    def feasible(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) + 1e-9 >= v for k, v in demand.items() if v > 0)

    def can_fit_now(self, demand: Dict[str, float]) -> bool:
        return all(self.available.get(k, 0.0) + 1e-9 >= v for k, v in demand.items() if v > 0)

    def utilization(self) -> float:
        u = 0.0
        for k, tot in self.total.items():
            if tot > 0:
                u = max(u, 1.0 - self.available.get(k, 0.0) / tot)
        return u


_spread_rr = {"i": 0}


def _note_rejections(explain: Optional[dict], view: Dict[str, NodeView],
                     demand: Dict[str, float]):
    """Fill an explain record's per-node rejection causes for the nodes the
    policy will never consider: dead, draining, or infeasible for the
    demand shape.  Causes use the bounded REJECT_CAUSES vocabulary
    (core/sched_explain.py) — they become event fields, never free-form."""
    if explain is None:
        return
    rejected = explain.setdefault("rejected", {})
    for nid, n in view.items():
        if not n.alive:
            rejected[nid] = "dead"
        elif not n.feasible(demand):
            # infeasible beats draining: a node that could NEVER host the
            # shape is a resource rejection whatever its drain state —
            # "draining" is reserved for nodes the drain is actually
            # costing us (feasible but routed around), which is what maps
            # a failed pick to NODE_DRAINING vs NO_RESOURCES
            rejected[nid] = "resources"
        elif n.draining:
            rejected[nid] = "draining"
    explain["candidates"] = len(view)


def pick_node(view: Dict[str, NodeView],
              demand: Dict[str, float],
              strategy="DEFAULT",
              local_node_id: Optional[str] = None,
              rng: random.Random | None = None,
              explain: Optional[dict] = None) -> Optional[str]:
    """Return the chosen node_id hex, or None if no feasible node exists.

    ``explain``, when a dict, is filled with the structured decision
    record: ``candidates`` (nodes in view), ``rejected`` ({node: cause}
    for every node the policy ruled out), ``chosen``.  The None-explain
    path pays nothing — the explain plane's callers (GCS scheduling
    loops, owner lease acquisition) opt in per decision."""
    rng = rng or random
    alive = {nid: n for nid, n in view.items()
             if n.alive and not n.draining}
    _note_rejections(explain, view, demand)

    def chose(nid: Optional[str]) -> Optional[str]:
        if explain is not None:
            explain["chosen"] = nid
        return nid

    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        n = alive.get(strategy.node_id)
        if n is not None and n.feasible(demand):
            return chose(strategy.node_id)
        if not strategy.soft:
            if explain is not None and strategy.node_id not in (
                    explain.get("rejected") or {}):
                # the pinned node exists but cannot take it — an affinity
                # miss, not a resource shortage
                explain.setdefault("rejected", {})[strategy.node_id] = \
                    "affinity"
            return chose(None)
        strategy = "DEFAULT"

    if isinstance(strategy, NodeLabelSchedulingStrategy):
        def match(n: NodeView, conds: Dict[str, List[str]]) -> bool:
            return all(n.labels.get(k) in vals for k, vals in conds.items())
        hard = [nid for nid, n in alive.items()
                if n.feasible(demand) and match(n, strategy.hard)]
        if not hard:
            if explain is not None:
                rej = explain.setdefault("rejected", {})
                for nid, n in alive.items():
                    if n.feasible(demand):
                        rej.setdefault(nid, "affinity")
            return chose(None)
        soft = [nid for nid in hard if match(alive[nid], strategy.soft)]
        pool = soft or hard
        return chose(rng.choice(pool))

    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        # Resolved earlier into a NodeAffinity by the PG manager; reaching here
        # means the bundle lookup failed.
        return chose(None)

    feasible = [nid for nid, n in alive.items() if n.feasible(demand)]
    if not feasible:
        return chose(None)
    fit_now = [nid for nid in feasible if alive[nid].can_fit_now(demand)]

    if strategy == "SPREAD":
        pool = fit_now or feasible
        pool = sorted(pool)
        _spread_rr["i"] = (_spread_rr["i"] + 1) % len(pool)
        return chose(pool[_spread_rr["i"]])

    # DEFAULT: hybrid policy.
    cfg = get_config()
    if (local_node_id is not None and local_node_id in alive
            and alive[local_node_id].can_fit_now(demand)
            and alive[local_node_id].utilization() < cfg.scheduler_spread_threshold):
        return chose(local_node_id)

    pool = fit_now or feasible
    ranked = sorted(pool, key=lambda nid: (alive[nid].utilization(), alive[nid].queue_len))
    k = max(cfg.scheduler_top_k_absolute,
            int(len(ranked) * cfg.scheduler_top_k_fraction))
    return chose(rng.choice(ranked[:k]))


def _ici_coord(n: NodeView) -> Optional[tuple]:
    """Parse the node's ICI torus coordinate label ("x,y" / "x,y,z")."""
    raw = (n.labels or {}).get("ici_coord")
    if not raw:
        return None
    try:
        return tuple(int(p) for p in str(raw).split(","))
    except ValueError:
        return None


def _ici_distance(a: tuple, b: tuple) -> int:
    """Manhattan hop distance between two ICI coordinates (a proxy for the
    number of ICI links a collective must traverse)."""
    return sum(abs(x - y) for x, y in zip(a, b))


def _ici_span(coords: List[tuple]) -> int:
    """Max pairwise hop distance — the diameter of a placement.  Contiguous
    sub-tori minimize this, which is what keeps psum/all-gather on short ICI
    paths instead of crossing the slice."""
    return max((_ici_distance(a, b) for a in coords for b in coords),
               default=0)


def pack_bundles(view: Dict[str, NodeView], bundles: List[Dict[str, float]],
                 strategy: str,
                 explain: Optional[dict] = None) -> Optional[List[str]]:
    """Explain-aware wrapper over the packing policies: fills the decision
    record (``rejected`` causes, ``bundles``, ``chosen`` placement) when a
    dict is passed, at zero cost otherwise."""
    placement = _pack_bundles(view, bundles, strategy, explain)
    if explain is not None:
        explain["chosen"] = placement
    return placement


def _pack_bundles(view: Dict[str, NodeView], bundles: List[Dict[str, float]],
                  strategy: str,
                  explain: Optional[dict] = None) -> Optional[List[str]]:
    """Placement-group bundle packing (reference: bundle_scheduling_policy.h)
    with the TPU extension SURVEY §2.3 calls for: nodes carrying
    ``tpu_slice``/``ici_coord`` labels are packed ICI-contiguously.

    Returns a node_id per bundle or None if infeasible.  STRICT_PACK puts
    every bundle on one node; PACK prefers few nodes — and among multi-node
    spills, same-slice nodes nearest (in ICI hops) to the nodes already
    chosen; SPREAD prefers distinct nodes; STRICT_SPREAD requires distinct
    nodes and, when the candidates have ICI coordinates, picks the seed whose
    greedy nearest-neighbor set minimizes the placement's ICI diameter (a
    contiguous sub-torus when one is free).
    """
    alive = {nid: NodeView(n.node_id, n.address, dict(n.total), dict(n.available),
                           n.labels, n.alive, n.queue_len)
             for nid, n in view.items() if n.alive and not n.draining}
    if explain is not None:
        # per-node rejection causes against the largest single bundle: the
        # shape a node must at least be able to hold to host any of them
        biggest = max(bundles, key=lambda b: sum(b.values())) if bundles \
            else {}
        _note_rejections(explain, view, biggest)
        explain["bundles"] = len(bundles)

    def try_place(order_nodes_for_bundle) -> Optional[List[str]]:
        placement: List[str] = []
        for i, b in enumerate(bundles):
            placed = False
            for nid in order_nodes_for_bundle(i, placement):
                n = alive[nid]
                if n.can_fit_now(b):
                    for k, v in b.items():
                        n.available[k] = n.available.get(k, 0.0) - v
                    placement.append(nid)
                    placed = True
                    break
            if not placed:
                return None
        return placement

    if strategy == "STRICT_PACK":
        for nid in sorted(alive, key=lambda x: alive[x].utilization()):
            saved = {k: dict(v.available) for k, v in alive.items()}
            p = try_place(lambda i, pl, nid=nid: [nid])
            if p is not None:
                return p
            for k, v in saved.items():
                alive[k].available = v
        return None
    def ici_key(nid: str, placed: List[str]):
        """(slice mismatch, ICI hops to the nearest already-placed node) —
        zeros when topology labels are absent, so plain clusters keep the
        original ordering."""
        n = alive[nid]
        placed_nodes = [alive[p] for p in dict.fromkeys(placed)]
        if not placed_nodes:
            return (0, 0)
        slices = {(p.labels or {}).get("tpu_slice") for p in placed_nodes}
        my_slice = (n.labels or {}).get("tpu_slice")
        slice_penalty = 0 if (my_slice in slices or my_slice is None) else 1
        c = _ici_coord(n)
        pcoords = [pc for pc in (_ici_coord(p) for p in placed_nodes)
                   if pc is not None]
        hops = (min(_ici_distance(c, pc) for pc in pcoords)
                if c is not None and pcoords else 0)
        return (slice_penalty, hops)

    if strategy == "PACK":
        return try_place(lambda i, pl: sorted(
            alive, key=lambda nid: (nid not in pl, *ici_key(nid, pl),
                                    alive[nid].utilization())))
    if strategy == "SPREAD":
        return try_place(lambda i, pl: sorted(
            alive, key=lambda nid: (pl.count(nid), alive[nid].utilization())))
    if strategy == "STRICT_SPREAD":
        coords = {nid: _ici_coord(alive[nid]) for nid in alive}
        if len(bundles) > 1 and sum(c is not None
                                    for c in coords.values()) >= len(bundles):
            # Topology-aware: greedy nearest-neighbor growth from every seed;
            # keep the placement with the smallest ICI diameter.
            best, best_span = None, None
            for seed in alive:
                if coords[seed] is None:
                    continue
                saved = {k: dict(v.available) for k, v in alive.items()}
                order = sorted(
                    (nid for nid in alive if coords[nid] is not None),
                    key=lambda nid: (_ici_distance(coords[seed], coords[nid]),
                                     alive[nid].utilization()))
                p = try_place(lambda i, pl, order=order:
                              [nid for nid in order if nid not in pl])
                for k, v in saved.items():
                    alive[k].available = v
                if p is not None:
                    span = _ici_span([coords[nid] for nid in p])
                    if best_span is None or span < best_span:
                        best, best_span = p, span
            if best is not None:
                return best
        return try_place(lambda i, pl: [nid for nid in sorted(
            alive, key=lambda n2: alive[n2].utilization()) if nid not in pl])
    raise ValueError(f"unknown placement strategy {strategy}")
