"""Object-plane "explain" layer: lifecycle flight recorder vocabulary,
copy-amplification ledger, and the single kill switch for every
``raytpu_object_*`` / ``raytpu_mem_*`` series.

The data plane moves bytes; this module makes the moves *inspectable*
instead of inferred from offline profiles (PROFILE_CORE.md,
BENCH_BROADCAST.json are snapshots — nobody could answer "where did this
object's bytes get copied, spilled, or stuck" from the runtime):

* :class:`ObjectEvent` — the closed set of object lifecycle transitions.
  Stamps ride a dedicated bounded ring in the GCS (``add_object_events`` /
  ``get_object_events`` / ``explain_object`` — the PR-10 ``sched_decision``
  ring pattern), flushed in batches by node agents and owners, and are
  TRANSITIONS ONLY: one event per state change, never per read.
* Copy-amplification ledger — every path that moves object payload bytes
  (put, get, promote, transfer land, spill, restore, re-home) declares its
  COPY CLASS here (:data:`COPY_CLASS`) and accounts its bytes into
  ``raytpu_object_bytes_total{path,copies}`` via a precomputed ``KEY_*``
  tag key.  ``sum(copies>0) / sum(all)`` per path is the headline
  regression gauge the zero-copy-put work (ROADMAP item 4) must drive
  down.  An AST lint (tests/test_metric_naming.py) pins call sites to the
  ``KEY_*`` constants, so a new byte-moving path cannot ship without
  declaring what it copies.
* ``object_metrics_enabled`` — the one kill switch (PR-2 registry
  discipline): off, hot paths pay a single cached boolean check, no
  ``raytpu_object_*``/``raytpu_mem_*`` series render, and no ring writes
  happen anywhere (agent buffers, GCS ring, transfer ring).

Reference: the Ray paper (1712.05889) makes per-object lineage + location
metadata the backbone of its object store; Podracer (2104.06272) argues
the control/data split only pays off when the data path is measurable.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .config import get_config


class ObjectEvent:
    """Closed vocabulary of object lifecycle transitions.

    These are EVENT FIELD values — the set bounds what the flight
    recorder can say, so new transitions are added here (and to the
    lifecycle diagram in ARCHITECTURE.md), never inlined at a call site.
    """

    #: owner allocated a shm segment for the object (large put / task
    #: result landing in plasma)
    CREATED = "CREATED"
    #: value stored inline in the owner's in-process memory store (small
    #: objects; travels inside RPC replies, never touches the shm store)
    INLINED = "INLINED"
    #: store entry sealed — bytes complete and immutable from here on
    SEALED = "SEALED"
    #: first read pin granted on a node's copy (0 -> 1 transition only;
    #: further pins on an already-pinned copy stamp nothing)
    PINNED = "PINNED"
    #: evicted copy written out, ``tier`` = local | external
    SPILLED = "SPILLED"
    #: spilled copy read back into a node's store, ``tier`` says whence
    RESTORED = "RESTORED"
    #: a node landed a copy it did not have (chunked pull or same-host
    #: zero-copy proxy attach; ``zero_copy`` marks the proxy case)
    TRANSFERRED = "TRANSFERRED"
    #: a draining node pushed its sole copy elsewhere (external tier or a
    #: live peer) before disappearing
    RE_HOMED = "RE_HOMED"
    #: owner-initiated free landed while reader pins were live — deletion
    #: deferred until the last pin releases
    FREE_DEFERRED = "FREE_DEFERRED"
    #: object deleted (owner refcount zero / store free completed)
    FREED = "FREED"

    ALL = frozenset({
        "CREATED", "INLINED", "SEALED", "PINNED", "SPILLED", "RESTORED",
        "TRANSFERRED", "RE_HOMED", "FREE_DEFERRED", "FREED",
    })


# ------------------------------------------------------------- kill switch

_enabled_cache: tuple = (None, False)


def enabled() -> bool:
    """One cached boolean per Config identity — the hot-path check."""
    global _enabled_cache
    cfg = get_config()
    if _enabled_cache[0] is not cfg:
        _enabled_cache = (cfg, bool(getattr(cfg, "object_metrics_enabled",
                                            False)))
    return _enabled_cache[1]


# --------------------------------------------------- copy-amplification ledger
#
# Copy classes: how many times a path copies the payload bytes it moves.
# "0" — zero-copy (mmap attach / pinned view / proxy), "1" — exactly one
# memcpy (serialize-into-arena, spill write, chunk landing), "n" — more
# than one (scratch-buffer verify paths, peer replication).  The class is
# part of the PATH DECLARATION below, not chosen at the call site: the
# ledger is the contract the zero-copy rewrite regresses against.

COPY_ZERO = "0"
COPY_ONE = "1"
COPY_N = "n"

#: path -> declared copy class.  EVERY byte-moving path in the object
#: plane appears here; the AST lint pins ledger call sites to the KEY_*
#: constants derived from this table, so adding a path means declaring
#: its class first.
COPY_CLASS: Dict[str, str] = {
    # classic/fallback put: owner serialize -> one write_into memcpy into
    # the arena mapping (the single put memcpy PROFILE_CORE round 6
    # measured at ~78% of the box memcpy ceiling).  The DEFAULT large-put
    # path is now the reserve-then-write zero-copy put, declared by
    # COPY_CLASS_ZC below and recorded under KEY_PUT_ZC — this row keeps
    # the 1-copy class of the estimate-miss / kill-switch fallback.
    "put": COPY_ONE,
    # small value -> owner memory store (one encode into the inline blob)
    "put_inline": COPY_ONE,
    # same-host large get over a pinned store mapping (plasma contract)
    "get": COPY_ZERO,
    # unpinned-fallback get: copy out + store_verify
    "get_copy": COPY_ONE,
    # inline->shm promotion of a borrowed small result
    "promote": COPY_ONE,
    # chunked pull landing (readinto the destination segment; the socket
    # read is the one copy on this side)
    "transfer_land": COPY_ONE,
    # same-host zero-copy proxy attach (bytes never move)
    "transfer_proxy": COPY_ZERO,
    # evicted entry written to the local disk / external tier
    "spill": COPY_ONE,
    # spilled copy read back into the store
    "restore": COPY_ONE,
    # drain-path re-home: read out of the store + write to tier/peer
    "re_home": COPY_N,
}

#: Alternate declared classes: a path whose DEFAULT pipeline differs from
#: its fallback declares both (same path label, different ``copies`` tag —
#: the ledger separates them by construction).  "put" class 0 is the
#: reserve-then-write zero-copy put (core/serialization.py
#: ``serialize_into``): the pickler targets the reserved arena range
#: directly, so no payload byte is ever materialized outside its source
#: and the store — the plasma/Arrow zero-copy-put convention.  Its
#: fallback (estimate miss, ``zero_copy_put_enabled=False``) stays the
#: 1-copy class above, pinned separately by tests/test_copy_discipline.py.
COPY_CLASS_ZC: Dict[str, str] = {
    "put": COPY_ZERO,
}

#: precomputed sorted tag-key tuples (Counter.inc_key discipline): one per
#: declared path, named KEY_<PATH>.  Call sites MUST use these constants —
#: the lint rejects inline tuples/strings (an undeclared path would be an
#: unbounded label value and an unaudited copy).
KEY_PUT = (("copies", COPY_CLASS["put"]), ("path", "put"))
KEY_PUT_ZC = (("copies", COPY_CLASS_ZC["put"]), ("path", "put"))
KEY_PUT_INLINE = (("copies", COPY_CLASS["put_inline"]), ("path", "put_inline"))
KEY_GET = (("copies", COPY_CLASS["get"]), ("path", "get"))
KEY_GET_COPY = (("copies", COPY_CLASS["get_copy"]), ("path", "get_copy"))
KEY_PROMOTE = (("copies", COPY_CLASS["promote"]), ("path", "promote"))
KEY_TRANSFER_LAND = (("copies", COPY_CLASS["transfer_land"]),
                     ("path", "transfer_land"))
KEY_TRANSFER_PROXY = (("copies", COPY_CLASS["transfer_proxy"]),
                      ("path", "transfer_proxy"))
KEY_SPILL = (("copies", COPY_CLASS["spill"]), ("path", "spill"))
KEY_RESTORE = (("copies", COPY_CLASS["restore"]), ("path", "restore"))
KEY_RE_HOME = (("copies", COPY_CLASS["re_home"]), ("path", "re_home"))


def _build_object_metrics():
    from ray_tpu.util.metrics import Counter
    return {
        "bytes": Counter(
            "raytpu_object_bytes_total",
            "object payload bytes moved by the data plane, by path and "
            "declared copy class (bytes_copied/bytes_moved per path is "
            "the copy-amplification gauge)",
            tag_keys=("path", "copies")),
    }


_object_metrics_get = None


def object_metrics() -> Optional[Dict[str, Any]]:
    global _object_metrics_get
    if not enabled():
        return None
    if _object_metrics_get is None:
        # deferred to first call: importing util.metrics at module import
        # time re-enters the ray_tpu package init (circular import)
        from ray_tpu.util.metrics import lazy
        _object_metrics_get = lazy(_build_object_metrics)
    return _object_metrics_get()


def ledger_record(key: tuple, nbytes: int) -> None:
    """Account ``nbytes`` moved through the path ``key`` (a KEY_*
    constant above — lint-enforced).  One dict-free counter bump; no-op
    when the kill switch is off."""
    m = object_metrics()
    if m is not None:
        m["bytes"].inc_key(key, nbytes)


def copy_amplification(values: Dict[tuple, float]) -> Optional[float]:
    """``bytes_copied / bytes_moved`` over a ``raytpu_object_bytes_total``
    values snapshot ({sorted-tag-key-tuple: bytes}).  Copy class "n"
    weighs 2 (a lower bound — the class means "more than one").  None
    when nothing moved."""
    weight = {COPY_ZERO: 0.0, COPY_ONE: 1.0, COPY_N: 2.0}
    moved = copied = 0.0
    for key, v in values.items():
        tags = dict(key)
        moved += v
        copied += weight.get(tags.get("copies", COPY_ONE), 1.0) * v
    if moved <= 0:
        return None
    return copied / moved
