"""Binary IDs for jobs, tasks, actors, objects, nodes, workers.

Design follows the reference's ``src/ray/common/id.h``: fixed-width random binary ids with
hex rendering; object ids embed the id of the task that produced them plus a return-index,
so ownership and lineage can be derived from the id itself (reference: ``ObjectID::ForTaskReturn``).
Sizes are chosen for compactness, not wire-compatibility.
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Optional

_NIL = b""

# Hot-path ID generation: one urandom syscall per id showed up at ~10 us/call
# in the task-submission path.  Uniqueness (not cryptographic strength) is
# what ids need, so hot ids (TaskID, put ObjectID — generated per call) use
# an 8-byte per-process random prefix + a monotonic counter, reseeded on fork
# (reference ids are likewise worker-prefix + counter composites,
# src/ray/common/id.h TaskID layout).  IMPORTANT: such ids share their prefix
# within a process, so they must never be truncated into identities (e.g.
# filenames) — NodeID/WorkerID/ActorID, which ARE truncated in places (store
# names, log names), stay fully random; they're created rarely.
_seed_lock = threading.Lock()
_seed_pid = -1
_seed_prefix = b""
_seq = itertools.count()


def _fast_unique16() -> bytes:
    global _seed_pid, _seed_prefix, _seq
    pid = os.getpid()
    if pid != _seed_pid:
        with _seed_lock:
            if pid != _seed_pid:
                _seed_prefix = os.urandom(8)
                _seq = itertools.count()
                _seed_pid = pid
    return _seed_prefix + next(_seq).to_bytes(8, "big")


class BaseID:
    SIZE = 16
    __slots__ = ("_bin", "_h")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(f"{type(self).__name__} must be {self.SIZE} bytes, got {len(binary)}")
        self._bin = binary
        self._h: Optional[int] = None

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def is_nil(self) -> bool:
        return self._bin == b"\x00" * self.SIZE

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __hash__(self):
        # hot path (dict keys in refcounting/stores): cache; cross-type
        # collisions are fine — __eq__ checks the type
        h = self._h
        if h is None:
            h = self._h = hash(self._bin)
        return h

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]})"

    def __reduce__(self):
        return (type(self), (self._bin,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 16


class TaskID(BaseID):
    SIZE = 16

    @classmethod
    def from_random(cls):  # hot path: one per task submission
        return cls(_fast_unique16())


class PlacementGroupID(BaseID):
    SIZE = 16


class ObjectID(BaseID):
    """TaskID (16B) + 4-byte big-endian return index."""

    SIZE = 20

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def from_random(cls):  # for ray.put objects: synthesize a put-task id
        return cls(_fast_unique16() + (0).to_bytes(4, "big"))

    def task_id(self) -> TaskID:
        return TaskID(self._bin[:16])

    def return_index(self) -> int:
        return int.from_bytes(self._bin[16:], "big")


class _Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v
