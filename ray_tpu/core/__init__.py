"""ray_tpu.core — the distributed runtime substrate (tasks, actors, objects).

Layering mirrors SURVEY.md §1 L1-L3: rpc/object_store/gcs/node_agent are the
"native layer" services; core_worker is the per-process runtime; api is the
public verb surface.  Import stays light (no jax) so worker startup is fast.
"""

from .api import (as_future, available_resources, cancel, cluster_resources,
                  exit_actor, get, get_actor, get_async, init, is_initialized,
                  kill, method, nodes,
                  put, remote, shutdown, timeline, wait)
from .common import (ActorDiedError, ActorUnavailableError, GetTimeoutError,
                     NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy,
                     ObjectLostError, OutOfMemoryError,
                     PlacementGroupSchedulingStrategy, RayTpuError,
                     TaskError, WorkerCrashedError)
from .generator import ObjectRefGenerator
from .object_ref import ObjectRef
from .placement_group import (PlacementGroup, placement_group,
                              placement_group_table, remove_placement_group)
from .runtime_context import get_runtime_context

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method", "get", "put", "wait",
    "kill", "cancel", "get_actor", "exit_actor", "get_async", "as_future", "nodes",
    "cluster_resources", "available_resources", "timeline", "ObjectRef",
    "ObjectRefGenerator", "OutOfMemoryError",
    "placement_group", "remove_placement_group", "placement_group_table",
    "PlacementGroup", "get_runtime_context", "TaskError", "RayTpuError",
    "ActorDiedError", "ActorUnavailableError", "GetTimeoutError", "ObjectLostError",
    "WorkerCrashedError", "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy", "PlacementGroupSchedulingStrategy",
]
