"""Streaming-generator task returns (``num_returns="streaming"``).

Mirrors the reference's ``StreamingObjectRefGenerator``
(``python/ray/_raylet.pyx:267``): a task whose function body is a generator
ships each yielded value to its owner the moment it is produced, instead of
buffering the whole output until the task finishes.  Ray Data's map operators
consume blocks this way so downstream operators start while the producer is
still running; Serve streams LLM tokens over it.

TPU-first redesign notes (vs the reference's C++ generator protocol):
* Yields ride the SAME worker->owner connection that per-task result
  streaming already uses (req_id -1 "gen_yield" frames, core_worker.py
  ``_make_result_streamer``), so ordering with the final task reply is the
  TCP stream's ordering — no separate object-report RPC or sequence protocol.
* Yield i becomes owner-owned object ``ObjectID.for_task_return(task_id, i)``
  — the same id scheme as static multi-returns, so lineage reconstruction
  re-runs the generator and re-stores every yield with no extra machinery.
* Backpressure is consumer-driven: the producing worker pauses once
  ``produced - consumed >= spec.generator_backpressure``; the owner sends a
  one-way ``generator_ack`` as the user's ``next()`` consumes items
  (reference: ``_generator_backpressure_num_objects``).
"""

from __future__ import annotations

import asyncio
from typing import List, Optional

from .ids import ObjectID, TaskID
from .object_ref import ObjectRef


class StreamState:
    """Owner-side bookkeeping for one streaming task (IO-loop confined except
    for the counters, which user threads read under the GIL)."""

    def __init__(self, task_id: TaskID, backpressure: int = 0):
        self.task_id = task_id
        self.backpressure = backpressure
        self.next_read = 0            # consumer cursor (user thread)
        self.available = 0            # yields stored so far
        self.total: Optional[int] = None   # set when the task finishes
        self.worker_addr: str = ""    # producer, for backpressure acks
        self.any_plasma = False
        self.abandoned = False
        #: lineage-reconstruction replay: store yields, expect no consumer
        self.replay = False
        self.event: Optional[asyncio.Event] = None  # lazily on the IO loop

    def signal(self):
        if self.event is not None:
            self.event.set()

    async def wait_change(self, timeout: Optional[float]):
        if self.event is None:
            self.event = asyncio.Event()
        self.event.clear()
        try:
            await asyncio.wait_for(self.event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def reset_for_retry(self):
        """A retried generator task replays its yields from index 0; already
        consumed items keep their (deterministic) object ids."""
        self.available = min(self.available, self.next_read)
        self.total = None


class ObjectRefGenerator:
    """Iterator of ObjectRefs for a ``num_returns="streaming"`` task.

    Supports both ``for ref in gen`` (blocking) and ``async for ref in gen``.
    When the task raises, the error becomes the stream's last item — the
    returned ref raises at ``get`` — matching the reference's semantics.
    """

    def __init__(self, worker, task_id: TaskID):
        self._w = worker
        self.task_id = task_id

    # -- sync protocol ----------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        from .rpc import run_async
        try:
            return run_async(self._next_async(None))
        except StopAsyncIteration:
            raise StopIteration from None

    def next(self, timeout: Optional[float] = None) -> ObjectRef:
        from .rpc import run_async
        try:
            return run_async(self._next_async(timeout))
        except StopAsyncIteration:
            raise StopIteration from None

    # -- async protocol ---------------------------------------------------

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        """Safe from any event loop: the wait itself always runs on the core
        worker's IO loop (where StreamState.event lives and is signalled)."""
        from .rpc import get_loop
        loop = get_loop()
        try:
            if asyncio.get_running_loop() is loop:
                return await self._next_async(None)
        except RuntimeError:
            pass
        cfut = asyncio.run_coroutine_threadsafe(self._next_async(None), loop)
        return await asyncio.wrap_future(cfut)

    async def _next_async(self, timeout: Optional[float]) -> ObjectRef:
        st = self._w.streams.get(self.task_id)
        if st is None:
            raise StopAsyncIteration
        while True:
            if st.next_read < st.available:
                i = st.next_read
                st.next_read += 1
                self._ack(st)
                return ObjectRef(ObjectID.for_task_return(self.task_id, i),
                                 owner=self._w.address)
            if st.total is not None and st.next_read >= st.total:
                self._w.streams.pop(self.task_id, None)
                raise StopAsyncIteration
            if not await st.wait_change(timeout):
                from .common import GetTimeoutError
                raise GetTimeoutError(
                    f"generator {self.task_id.hex()[:12]} produced nothing "
                    f"within {timeout}s")

    def _ack(self, st: StreamState):
        """Tell the producer a slot freed up (only when backpressure is on —
        the ack is pure overhead otherwise).  Runs on the IO loop (called
        from _next_async), so the one-way notify is fired as a loop task."""
        if not st.backpressure or not st.worker_addr:
            return
        try:
            client = self._w.worker_clients.get(st.worker_addr)
            asyncio.ensure_future(client.notify(
                "generator_ack", task_id=self.task_id,
                consumed=st.next_read))
        except Exception:
            pass  # producer finished/died: nothing to unblock

    def try_next(self) -> Optional[ObjectRef]:
        """Non-blocking next: a ref if one is already available, else None
        (poll-loop integration point — Data's streaming executor drives
        generators this way without parking its scheduling loop)."""
        st = self._w.streams.get(self.task_id)
        if st is None or st.next_read >= st.available:
            return None
        return self.__next__()

    # -- lifecycle ---------------------------------------------------------

    def completed(self) -> bool:
        st = self._w.streams.get(self.task_id)
        return st is None or (st.total is not None
                              and st.next_read >= st.total)

    def __del__(self):
        # Dropping the generator abandons unconsumed items: build-and-drop a
        # ref for each stored-but-unread yield so refcounting frees them, and
        # hand the producer an unbounded backpressure credit so a generator
        # parked in wait_capacity doesn't stall until its 600s timeout (e.g.
        # an HTTP client that disconnected mid-stream).
        try:
            st = self._w.streams.pop(self.task_id, None)
            if st is None:
                return
            st.abandoned = True
            for i in range(st.next_read, st.available):
                ObjectRef(ObjectID.for_task_return(self.task_id, i),
                          owner=self._w.address)
            if st.backpressure and st.worker_addr:
                from .rpc import get_loop
                client = self._w.worker_clients.get(st.worker_addr)
                asyncio.run_coroutine_threadsafe(
                    client.notify("generator_ack", task_id=self.task_id,
                                  consumed=1 << 62), get_loop())
        except Exception:
            pass


__all__ = ["ObjectRefGenerator", "StreamState"]
