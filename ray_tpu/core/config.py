"""Central config table for the runtime.

Mirrors the *design* of the reference's ``RAY_CONFIG`` macro table
(``src/ray/common/ray_config_def.h:18`` — 204 env-overridable tunables handed to every
process), re-done as a typed Python dataclass whose every field can be overridden with an
``RAYTPU_<NAME>`` environment variable or a ``_system_config`` dict passed to
:func:`ray_tpu.init`.  Worker processes receive the serialized config via their
environment so the whole cluster sees one consistent table.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict

_ENV_PREFIX = "RAYTPU_"


@dataclasses.dataclass
class Config:
    # -- object store ------------------------------------------------------
    #: Objects <= this many bytes are stored inline in the owner's in-process
    #: memory store and travel inside RPC replies (reference:
    #: ``max_direct_call_object_size``, ray_config_def.h).
    max_direct_call_object_size: int = 100 * 1024
    #: Capacity of the per-node shared-memory store in bytes (0 = 30% of RAM).
    object_store_memory: int = 0
    #: Zero-copy put (reserve-then-write): large puts reserve an arena
    #: range up front from a cheap size estimate and the pickler's
    #: out-of-band buffers land DIRECTLY into the reserved segment
    #: (parallel memoryview gather-write, no intermediate ``bytes``
    #: anywhere) — the ledger's ``put/copies=0`` class.  False restores
    #: the exact prior path (serialize, then one ``write_into`` memcpy):
    #: the ``--ab-zcput`` off arm and the production kill switch.
    zero_copy_put_enabled: bool = True
    #: Gather-write lanes for the zero-copy put landing: buffers >= the
    #: stripe threshold are striped over this many copier threads (numpy
    #: ``copyto`` releases the GIL, so the landing runs at aggregate
    #: memory bandwidth instead of the single-thread memcpy ceiling).
    #: 0 = auto (min(8, cpu count)); 1 = serial landing.
    put_gather_threads: int = 0
    #: BASE chunk size for node-to-node object transfer — the chunk
    #: ledger's bookkeeping/steal/partial-serving unit.  The adaptive
    #: controller claims RUNS of consecutive base chunks (see
    #: ``object_transfer_chunk_max``), so this stays small enough for
    #: fine-grained striping (late-folded relays of a broadcast must
    #: still find claimable chunks) without capping per-request size —
    #: growth recovers large requests on healthy links.
    object_transfer_chunk_bytes: int = 2 * 1024 * 1024
    #: Adaptive per-request ceiling: a source's claim run grows
    #: geometrically under clean completions toward this many bytes and
    #: shrinks on timeout/steal — replacing the fixed chunk size on the
    #: wire.  Growth re-clamps against the receiver's ``largest_free``
    #: arena block so a grown request can never force a spill mid-pull.
    #: <= object_transfer_chunk_bytes disables growth (fixed chunks).
    object_transfer_chunk_max: int = 64 * 1024 * 1024
    #: Parallel sockets per (puller, source) pair: in-flight chunk
    #: requests to one source spread (sticky per chunk) over this many
    #: DEDICATED bulk-channel connections (core/bulk_transfer.py —
    #: threaded blocking sockets, sendall/recv_into straight between shm
    #: and the kernel), so multi-MB replies stream concurrently instead
    #: of serializing head-of-line on one socket and one event loop.
    #: 1 = the historical single shared asyncio connection per peer (the
    #: --ab-zcput off arm).
    transfer_sockets_per_source: int = 4
    #: TOTAL in-flight chunks per object pull, across all sources (the
    #: chunk-ledger stripe's global window).
    object_transfer_parallelism: int = 16
    #: In-flight chunks per SOURCE within one pull (per-source window of
    #: the multi-source stripe).
    object_transfer_per_source_window: int = 4
    #: Per-chunk RPC deadline: a chunk slower than this is failed and
    #: re-striped onto another source (the generic rpc_call_timeout_s is
    #: far too patient for an 8 MB read).
    object_transfer_chunk_timeout_s: float = 30.0
    #: Hedge (work-steal) an in-flight chunk held by another source longer
    #: than this many seconds; 0 = adaptive (2x the median completed-chunk
    #: time, floored at 0.25 s).
    object_transfer_steal_after_s: float = 0.0
    #: Chunk-fetch failures before a source is dropped from the stripe.
    object_transfer_max_source_failures: int = 3
    #: Mid-pull source refresh period: re-poll the owner's location view
    #: and re-probe partial sources' advertised ranges this often.  (The
    #: hot case — a paused relay whose ranges just widened — is probed
    #: event-driven with a 50 ms debounce; this tick only folds in newly
    #: REGISTERED sources, so a broadcast engages relays within its first
    #: chunk-times.)
    object_transfer_source_refresh_s: float = 0.1
    #: Fail a pull that lands NO chunk for this long (all sources dead /
    #: unreachable and the owner offers nothing new).
    object_transfer_stall_timeout_s: float = 60.0
    #: Optional per-chunk checksum on the byte path (native CRC-32C when
    #: the extension builds, zlib.crc32 otherwise): a mismatched chunk is
    #: rejected and re-pulled instead of sealing a corrupt object.
    object_transfer_checksum: bool = False
    #: Partial-object serving: a puller advertises + serves the chunk
    #: ranges it already holds, so an N-node broadcast pipelines through
    #: in-progress pullers instead of waiting for full copies.
    object_transfer_partial_serving: bool = True
    #: Max concurrent inbound object pulls admitted per node.
    object_pull_max_concurrency: int = 8
    #: Use the native C++ shm arena allocator for the store (falls back to
    #: Python file-per-object when g++ is unavailable).
    object_store_use_native_pool: bool = True
    #: Prefault the arena's pages at store startup (MADV_POPULATE_WRITE) so
    #: steady-state puts run at memcpy speed instead of page-fault speed
    #: (plasma pre-touches its dlmalloc arena the same way).
    object_store_prefault: bool = True
    #: Max tasks sent to one leased worker in a single batched push RPC
    #: (reference: ``max_tasks_in_flight_per_worker``).  64 (up from 16):
    #: with per-tick result-push coalescing, bigger batches amortize the
    #: owner-loop per-batch costs without serializing whole-node
    #: parallelism (the pump still splits the queue over expected
    #: capacity) — measured +20% on the 100k-task drain on an 8-worker
    #: box.
    max_tasks_in_flight_per_worker: int = 64
    #: Max actor calls coalesced into one batched submission RPC per handle.
    actor_call_pipeline: int = 32

    # -- submission fast path ---------------------------------------------
    #: Task/actor RETURN values at or under this many bytes travel back
    #: inside the task-reply frame and land directly in the caller's
    #: in-process store — no worker-side ``store_create`` and no
    #: caller-side fetch RPC per result.  0 disables result inlining
    #: entirely (every result goes through the shm store; the perf A/B's
    #: "off" arm).  Streaming-generator yields are NOT governed by this
    #: knob — they keep the plain ``max_direct_call_object_size``
    #: threshold so the yield pipeline is unchanged.
    inline_result_max_bytes: int = 100 * 1024
    #: TaskSpec template cache: the invariant portion of a spec (function
    #: descriptor, options, runtime-env hash) is wire-encoded once per
    #: (function, options) pair and interned by hash on the receiving
    #: worker, so each submission ships only args + ids (core/spec_cache.py).
    spec_cache_enabled: bool = True
    #: Bounded LRU size of the spec template cache, both sender side
    #: (encoded template blobs) and receiver side (interned prototypes).
    spec_cache_max_entries: int = 512
    #: Lease pipelining: when a pool has unmet demand it requests this many
    #: leases BEYOND the current deficit, so the next submission burst finds
    #: a granted worker instead of paying a lease round trip.  0 disables.
    lease_pipeline_window: int = 1
    #: Return a leased worker after it has executed this many tasks even if
    #: more are queued (bounds lease reuse so one pool cannot monopolise a
    #: node's workers; 0 = unlimited reuse).
    lease_reuse_max_tasks: int = 0
    #: Owner-side idle-lease return delay in milliseconds: a leased worker
    #: idle this long with nothing queued is returned to the agent.
    lease_idle_return_ms: float = 2000.0
    #: Max leases requested from one agent in a single batched
    #: ``request_worker_leases`` RPC (same-tick submission bursts coalesce
    #: their lease demand into one control-plane round trip).
    submit_batch_max: int = 16
    # -- scale envelope (million-task submission pipeline) -----------------
    #: Owner-side admission control: max tasks in flight (submitted but not
    #: yet finished/failed) per CoreWorker before ``.remote()`` blocks on
    #: the waitable admission gate.  A driver firing 1M submissions
    #: degrades to smooth pipelining at this window instead of building
    #: 1M specs of owner state and flooding the agents' lease queues.
    #: 0 disables admission control (unbounded in-flight).
    submit_inflight_limit: int = 50_000
    #: Bounded submission flush window in milliseconds: the first
    #: submission of a burst arms the flush; further same-window calls ride
    #: the same flush.  0 flushes on the next loop tick (lowest latency);
    #: >0 trades up to that much latency for bigger push batches.  A buffer
    #: reaching ``submit_flush_max`` flushes immediately regardless.
    submit_flush_window_ms: float = 0.0
    #: Flush the submit buffer immediately once it holds this many entries,
    #: even inside an armed ``submit_flush_window_ms`` window.
    submit_flush_max: int = 512
    #: Master switch for submission batching (the scale-envelope A/B knob):
    #: False degrades to one task per push RPC, one lease per request RPC,
    #: one actor call per batch — the unbatched submission plane.
    submit_batching_enabled: bool = True
    #: Hash-shard count of the GCS hot tables (KV, actor table): rehash
    #: pauses are bounded by the largest shard and maintenance scans can
    #: yield between shards (core/sharded_table.py).
    gcs_table_shards: int = 16
    # -- horizontal control plane (multi-process GCS + submission lanes) ---
    #: Number of GCS shard PROCESSES (core/gcs_shard.py): the hot,
    #: key-partitionable control-plane traffic (KV by namespace, task/
    #: object/sched event fan-in) is served by N subprocesses — each with
    #: its own event loop, RPC server, and snapshot file — fronted by the
    #: router (core/gcs.py), which keeps the globally-ordered concerns
    #: (nodes, jobs, actors, PG 2PC, pubsub).  0 disables (single-process
    #: GCS, exactly the pre-shard behavior).  Changing this count between
    #: incarnations of a persisted GCS is NOT supported: shard snapshot
    #: files restore by shard index (see ARCHITECTURE.md "Horizontal
    #: control plane").
    gcs_shard_processes: int = 0
    #: Parallel client connections to the GCS router/shards per process
    #: (the owner's kv + event-flush traffic fans over these; each extra
    #: connection lives on its own IO-loop lane thread).  1 = the single
    #: shared connection (historical behavior).
    gcs_client_connections: int = 1
    #: IO-loop lanes for the owner's worker/agent connections: addresses
    #: are spread (sticky) over this many loop threads, so the per-frame
    #: pickle/unpickle and socket syscalls of different peers' connections
    #: overlap on separate OS threads instead of serializing on one loop.
    #: Per-lane FIFO ordering is preserved (an address keeps its lane).
    #: 1 = everything on the default loop (historical behavior).
    agent_client_connections: int = 1
    #: Completion batching (the PR-13 drain fast path): workers coalesce
    #: same-tick task results into one ``task_result_batch`` push frame,
    #: and owned-ref batch gets wait on ONE shared future instead of a
    #: per-ref coroutine + Event gather.  The A/B off arm restores the
    #: per-result frame / per-ref wait plane.
    completion_batching_enabled: bool = True
    #: Owner-side serialization thread pool: spec wire-encoding (template
    #: cache + args pickling) for push batches runs on this many pool
    #: threads instead of the RPC loop, overlapping pickle time with the
    #: loop's socket work.  0 encodes inline on the loop (historical).
    owner_serialize_threads: int = 0
    #: Native submission plane (the per-task owner fast path): warm-path
    #: push batches wire-encode into ONE packed binary frame
    #: (spec_cache.pack_specs — C extension when built, byte-identical
    #: pure-Python fallback otherwise), submitted TaskSpecs are slotted
    #: objects recycled through a free-list, and per-ref refcount
    #: mutations take one lock per batch.  False restores the prior
    #: per-spec tuple wire path, ctor-built specs, and per-ref locking
    #: exactly (the ``perf.py --ab-submitplane`` off arm).
    submit_plane_native_enabled: bool = True
    #: Task-event payload sampling: histograms and the submission-plane
    #: counters observe EVERY task; full per-task event trails
    #: (SUBMITTED/RUNNING records) are emitted for 1-in-N tasks.
    #: Terminal events (FINISHED/FAILED) are NEVER sampled away, so the
    #: state rollup still counts every task and ``raytpu explain``
    #: answers for unsampled tasks from their terminal record.
    #: 0 or 1 = full trails for every task (historical behavior).
    task_event_sample_n: int = 0
    #: Capacity of the TaskSpec free-list (submitted specs are recycled
    #: at terminal completion instead of re-allocated per call).
    #: 0 disables recycling.
    spec_freelist_max: int = 4096
    #: Run the EMBEDDED control plane (the GCS server and node agent that
    #: ``init(address=None)`` boots inside the driver process) on their
    #: own IO-loop threads instead of the driver's shared loop — the
    #: single-loop ceiling fix for the one-process head: GCS handlers,
    #: agent lease/store handlers, and the owner submission path stop
    #: contending for one thread.  Off by default (tests may reach into
    #: embedded components assuming loop-0 confinement).
    control_plane_io_lanes: bool = False
    #: Per-topic pubsub log length at the GCS.  Each topic keeps its own
    #: seq-ordered log (polls bisect past their cursor instead of scanning
    #: global traffic); a subscriber lagging more than this many events on
    #: one topic misses the trimmed window, same as the old global ring.
    gcs_pubsub_topic_log_len: int = 4000
    #: Agent-side lease-queue depth bound: a lease request arriving at an
    #: agent whose queue is already this deep is answered with a
    #: ``backpressure`` reply instead of parking — the owner backs off and
    #: re-picks a node, so a 1M-task burst cannot grow an unbounded parked
    #: queue on one agent.  0 disables the bound.
    lease_queue_max_depth: int = 4096
    #: How long an owner waits after a lease ``backpressure`` reply before
    #: re-evaluating its cluster view and retrying.
    lease_backpressure_retry_s: float = 0.2
    #: Spill directory ("" = default under /tmp; "off" disables spilling).
    object_spilling_dir: str = ""
    #: Spill when store utilization exceeds this fraction.
    object_spilling_threshold: float = 0.8
    #: External (fsspec-backed) spill tier base URI — e.g. ``gs://bucket/
    #: prefix`` in production, ``file:///dir`` in tests; "" disables.  When
    #: set, spill-on-evict writes the object once to
    #: ``{uri}/{object_id}.obj`` and registers the URI with the OWNER as a
    #: location that is not a node, so the object survives losing the node
    #: that spilled it and any node's pull path can restore it (the
    #: preemption-survivability tier; reference: ray's
    #: ``object_spilling_config`` smart_open/fsspec spill targets).
    object_spilling_external_uri: str = ""

    # -- scheduling --------------------------------------------------------
    #: Top-k fraction of feasible nodes considered by the hybrid policy
    #: (reference: ``scheduler_top_k_fraction``, hybrid_scheduling_policy.h:51).
    scheduler_top_k_fraction: float = 0.2
    scheduler_top_k_absolute: int = 1
    #: Prefer the local node until its critical-resource utilization passes
    #: this threshold (reference: ``scheduler_spread_threshold``).
    scheduler_spread_threshold: float = 0.5
    #: Lease reuse window: an idle leased worker is returned to the pool after
    #: this many seconds (reference: ``idle_worker_killing_time_threshold_ms``).
    idle_worker_timeout_s: float = 2.0
    #: Escrow grace for distributed refcounting: delay owner-side frees and
    #: borrower-side remove-notes so refs in flight between processes (task
    #: results / actor replies) can be registered by the receiver before the
    #: owner evaluates "no references left".
    ref_escrow_grace_s: float = 10.0
    #: How long an owner honors a producer's escrow hold on a contained ref
    #: before assuming the consumer died (the hold is normally released
    #: explicitly the moment the consumer registers its borrow — this expiry
    #: only bounds the leak window when a consumer crashes mid-handoff).
    escrow_hold_expiry_s: float = 60.0
    #: Max workers a node agent will spawn beyond configured CPU count for
    #: blocked-on-get tasks.
    max_extra_workers: int = 2

    # -- workers -----------------------------------------------------------
    #: Workers pre-started per node at boot (reference: ``prestart_worker_first_driver``).
    prestart_workers: int = 0
    #: Seconds to wait for a worker process to register before declaring it dead.
    worker_register_timeout_s: float = 30.0

    # -- fault tolerance ---------------------------------------------------
    #: Default task max_retries (reference: ``task_retry_delay_ms`` family).
    default_task_max_retries: int = 3
    #: Base delay of the task-retry exponential backoff; retry n sleeps
    #: ~``base * backoff**(n-1)`` capped at ``task_retry_max_delay_s``,
    #: jittered so retry storms under node loss don't synchronize.
    task_retry_delay_s: float = 0.05
    task_retry_max_delay_s: float = 2.0
    task_retry_backoff: float = 2.0
    #: Enable lineage reconstruction of lost objects
    #: (reference: ``lineage_pinning_enabled``, ray_config_def.h:155).
    lineage_reconstruction_enabled: bool = True
    #: Node agent heartbeat period / failure threshold
    #: (reference: GcsHealthCheckManager defaults).
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5
    #: Memory-monitor victim policy: "group_by_owner" kills the newest
    #: worker of the owner with the LARGEST fan-out (reference:
    #: worker_killing_policy_group_by_owner.h:85 — the biggest submitter is
    #: both the likeliest cause and the cheapest to retry); "retriable_lifo"
    #: kills the newest leased task worker regardless of owner.
    oom_worker_killing_policy: str = "group_by_owner"
    #: OOM kills of the SAME task use this separate retry budget (reference:
    #: task_oom_retries) — after this many memory-monitor kills the task
    #: fails with a typed, actionable OutOfMemoryError instead of retrying
    #: forever; -1 = unlimited.
    task_oom_retries: int = 3

    # -- rpc ---------------------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    rpc_call_timeout_s: float = 120.0
    #: Retrying idempotent client (``RpcClient.call_retry``): bounded
    #: attempts with exponential backoff + full jitter under one shared
    #: per-call deadline (reference: retryable gRPC clients).
    rpc_retry_max_attempts: int = 5
    rpc_retry_base_delay_s: float = 0.05
    rpc_retry_max_delay_s: float = 2.0
    #: Server-side idempotency-token dedup window: a retried mutating RPC
    #: carrying the same client-stamped token within this window replays
    #: the recorded reply instead of re-executing the handler.
    rpc_dedup_window_s: float = 600.0
    #: Chaos injection (reference: ray's chaos_network_delay.yaml release
    #: harness).  ``chaos_spec`` is a JSON FaultInjector spec (see
    #: ``core/chaos.py``): per-method/per-link delay, frame drops,
    #: fail-before/after-commit, partitions, and a seeded worker-kill
    #: schedule.  Set RAYTPU_CHAOS_SPEC before booting and every process
    #: inherits it (workers via RAYTPU_CONFIG_JSON); runtime control via
    #: GCS chaos_set/chaos_clear and `raytpu chaos`.
    chaos_spec: str = ""
    #: Legacy single-knob harness: every outbound RPC frame is delayed this
    #: many ms (now a one-rule spec on the same injector); 0 disables.
    chaos_rpc_delay_ms: float = 0.0
    #: Actor __init__ runs arbitrary user code (model loads, XLA compiles —
    #: an LLM replica warms minutes of prefill buckets): the creation call
    #: must not be bounded by the generic RPC timeout, or the agent kills
    #: the worker mid-compile and the GCS retries forever.
    actor_creation_timeout_s: float = 3600.0

    # -- pubsub / syncer ---------------------------------------------------
    #: Resource-view gossip period (reference: RaySyncer, ray_syncer.h:86).
    resource_broadcast_period_s: float = 0.1

    # -- OOM defense -------------------------------------------------------
    #: Kill workers when node memory passes the threshold (reference:
    #: memory_monitor.h:52 + worker_killing_policy.h:64 retriable-LIFO).
    memory_monitor_enabled: bool = True
    memory_monitor_interval_s: float = 1.0
    memory_usage_threshold: float = 0.95

    # -- race / stall detection -------------------------------------------
    #: Opt-in event-loop stall detector (util/loop_monitor.py): a sibling
    #: thread heartbeats each runtime process's IO loop and records a
    #: WARNING event with the blocking stack when an echo is overdue —
    #: the asyncio analogue of the reference's TSAN/sanitizer CI builds
    #: (SURVEY §5.2).
    loop_monitor_enabled: bool = False
    loop_monitor_threshold_s: float = 0.5

    # -- metrics -----------------------------------------------------------
    metrics_export_enabled: bool = True
    #: Serve-plane observability (serve/observability.py): per-request
    #: latency/TTFT/TPOT histograms, queue-depth gauges, batch occupancy,
    #: KV/prefix-cache gauges, request-scoped stage spans, and the rolling
    #: SLO window the controller aggregates.  One kill switch sheds ALL of
    #: it (the serve hot path keeps only a boolean check per request) for
    #: A/B overhead measurement — same discipline as rpc_metrics_enabled.
    serve_metrics_enabled: bool = True
    #: Prefix-cache-aware routing: replica heartbeats carry a bounded
    #: digest of the prefix cache's first-page block hashes; the router
    #: scores its two power-of-two-choices candidates by estimated prefix
    #: overlap x in-flight load.  Off (or on stale/absent digests) the
    #: router falls back to pure p2c — identical to the pre-digest path.
    serve_prefix_routing_enabled: bool = True
    #: Cap on first-page block hashes carried per heartbeat digest.  Keeps
    #: the health-check payload and the router's membership set O(small);
    #: the newest entries win (most recently inserted prefixes).
    serve_prefix_digest_max: int = 32
    #: How strongly a digest hit discounts a candidate's load score:
    #: score = (inflight + 1) * (1 - weight * hit).  0 disables the
    #: discount (pure p2c); 1 makes any hit beat any miss at equal load.
    serve_prefix_routing_weight: float = 0.5
    #: Rolling window over which each replica computes its TTFT
    #: percentiles + queue-depth signal for the controller (the SLO
    #: autoscaler input).  Samples older than this age out.
    serve_slo_window_s: float = 60.0
    #: Train-plane observability (train/observability.py): per-step
    #: wall-clock decomposition (data_wait/host_to_device/step_compute/
    #: checkpoint), first-call compile split out, running MFU + goodput,
    #: device memory gauges, per-step trace spans, and the per-rank
    #: snapshot rollup into train.Result / train.status().  One kill
    #: switch sheds ALL of it (the train loop keeps one boolean check per
    #: phase/report) for A/B overhead measurement — same discipline as
    #: serve_metrics_enabled.
    train_metrics_enabled: bool = True
    #: Cap on per-step trace spans emitted per second per rank (the
    #: task_stage_events_per_s discipline): step/stage HISTOGRAMS observe
    #: every step regardless; only the timeline payload samples beyond
    #: this rate — real accelerator steps run well under it, CPU toy
    #: loops get a sampled timeline.  <= 0 means unlimited.
    train_step_spans_per_s: int = 25
    #: Scheduler/control-plane saturation observability
    #: (core/sched_explain.py): per-event-loop busy-fraction sampling
    #: (``raytpu_loop_busy_fraction{process}``), per-GCS-handler busy
    #: seconds (``raytpu_gcs_handler_seconds{method}``), owner-side
    #: serialization/flush time histograms (``raytpu_sched_owner_*``) and
    #: per-node backpressure-reject counters
    #: (``raytpu_sched_backpressure_total``).  ONE kill switch sheds every
    #: raytpu_sched_*/raytpu_loop_*/raytpu_gcs_* series (hot paths keep a
    #: single boolean check) for A/B overhead measurement — same
    #: discipline as rpc_metrics_enabled.
    sched_metrics_enabled: bool = True
    #: Bounded ring of scheduler decision records kept by the GCS
    #: (candidates/rejection-causes/outcome per pick_node / pack_bundles /
    #: lease-acquisition decision) — the ``raytpu explain`` /
    #: ``state.explain`` backing store.
    sched_decision_ring_len: int = 2048
    #: Decision records older than this age out of the ring (and are
    #: dropped from query replies) — a debug trail, not a history DB.
    sched_decision_max_age_s: float = 600.0
    #: Stamp queued tasks LEASE_QUEUED only after a lease request has been
    #: outstanding this long — a fast grant must not pay a per-task
    #: pending event on the happy path.
    sched_pending_stamp_after_s: float = 0.5
    #: Cap on per-transition pending-reason stamps: when a lease pool's
    #: reason changes, at most this many queued specs get the event (the
    #: decision record carries the full queue count) — a 50k-deep pool
    #: flip must not pin the IO loop stamping every spec.
    sched_explain_stamp_max: int = 1000
    #: Object-plane observability (core/object_explain.py): the per-object
    #: lifecycle flight recorder (CREATED/SEALED/SPILLED/RESTORED/
    #: TRANSFERRED/RE_HOMED/FREED transition events into a bounded GCS
    #: ring), the copy-amplification ledger
    #: (``raytpu_object_bytes_total{path,copies}``), arena fragmentation +
    #: spill-tier gauges (``raytpu_mem_*``), and the per-pull transfer
    #: flight-recorder ring behind ``state.transfers()``.  ONE kill switch
    #: sheds every ``raytpu_object_*``/``raytpu_mem_*`` series AND all
    #: ring writes (hot paths keep a single cached boolean check) for A/B
    #: overhead measurement — same discipline as sched_metrics_enabled.
    object_metrics_enabled: bool = True
    #: Bounded ring of object lifecycle events kept by the GCS (the
    #: ``state.explain_object`` / ``raytpu explain <oid>`` backing store —
    #: the sched_decision ring pattern applied to the data plane).
    object_event_ring_len: int = 4096
    #: Object events older than this age out of the ring (and are dropped
    #: from query replies) — a debug trail, not a history DB.
    object_event_max_age_s: float = 600.0
    #: Bounded per-agent ring of completed-pull ChunkLedger end-states
    #: (per-source bytes/steals/failures/relay fraction) behind
    #: ``state.transfers()`` / ``raytpu transfers``.
    object_transfer_ring_len: int = 256
    #: Ref-debt detector: a read pin held longer than this by a live
    #: consumer is reported as a leak suspect by ``raytpu memory --leaks``
    #: (dead consumers' pins are drained by the liveness sweep already;
    #: this catches the live-but-forgot case).
    object_pin_leak_ttl_s: float = 300.0
    #: Dashboard cluster-metrics history (dashboard/history.py): the head
    #: scrapes every node agent's /metrics on this period into a bounded
    #: ring buffer covering this window, derives counter rates, and serves
    #: GET /api/metrics/history (and the freshest sample on /api/metrics).
    metrics_history_window_s: float = 600.0
    metrics_scrape_period_s: float = 5.0
    #: Per-method RPC client/server latency histograms + byte counters
    #: (core/rpc.py).  Cheap (one histogram observe per call) but the hot
    #: path can shed it entirely for A/B overhead measurement.
    rpc_metrics_enabled: bool = True
    task_events_enabled: bool = True
    #: Per-task lifecycle stage breakdown (queue/dep_fetch/arg_deser/
    #: execute/result_put stamps + STAGES events + the stage histogram).
    #: Rides the task-event stream, so task_events_enabled=False also
    #: disables it; this knob sheds ONLY the breakdown.
    task_stage_breakdown_enabled: bool = True
    #: Cap on per-task STAGES events emitted per second per executor.  The
    #: stage HISTOGRAM observes every task regardless (percentiles stay
    #: exact); only the per-task timeline payload is sampled beyond this
    #: rate, bounding the event-pipeline overhead under small-task floods
    #: (reference: task event buffer sampling).  <= 0 means unlimited.
    task_stage_events_per_s: int = 200
    #: Ring buffer size for task state-transition events
    #: (reference: TaskEventBuffer, task_event_buffer.h).
    task_events_max_buffer: int = 100_000
    #: Health plane (util/health.py): rule-based anomaly detection over
    #: the existing observability surfaces, typed Alerts into a bounded
    #: GCS ring, ``raytpu_health_alerts_total{rule,severity}`` /
    #: ``raytpu_health_active_alerts{rule}``.  ONE kill switch: off means
    #: zero raytpu_health_* series AND no detector CPU (the head scrape
    #: hook and the GCS snapshot hook skip evaluation entirely); the ring
    #: stays queryable and ``raytpu doctor`` still evaluates on demand.
    health_metrics_enabled: bool = True
    #: Bounded ring of alert transition events kept by the GCS (the
    #: sched_decision ring pattern applied to health).
    health_ring_len: int = 512
    #: Alert transitions older than this age out of the ring.
    health_alert_max_age_s: float = 3600.0
    #: Hysteresis: a rule's value must hold at/above raise_at this long
    #: before an alert raises (rules that ARE their own sustain signal —
    #: EVENTS_SHED, NODE_FLAPPING — override to 0).
    health_raise_hold_s: float = 10.0
    #: Hysteresis: an active alert clears only after its value holds
    #: at/below clear_at this long (and the alert is at least this old)
    #: — the min-hold that stops raise/clear flapping.
    health_min_hold_s: float = 30.0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "Config":
        return cls(**json.loads(s))

    @classmethod
    def from_env(cls, overrides: Dict[str, Any] | None = None) -> "Config":
        """Build a config: defaults < env vars < explicit overrides."""
        kwargs: Dict[str, Any] = {}
        for f in dataclasses.fields(cls):
            env = os.environ.get(_ENV_PREFIX + f.name.upper())
            if env is not None:
                if f.type in ("int", int):
                    kwargs[f.name] = int(env)
                elif f.type in ("float", float):
                    kwargs[f.name] = float(env)
                elif f.type in ("bool", bool):
                    kwargs[f.name] = env.lower() in ("1", "true", "yes")
                else:
                    kwargs[f.name] = env
        if overrides:
            unknown = set(overrides) - {f.name for f in dataclasses.fields(cls)}
            if unknown:
                raise ValueError(f"Unknown _system_config keys: {sorted(unknown)}")
            kwargs.update(overrides)
        return cls(**kwargs)


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        env = os.environ.get("RAYTPU_CONFIG_JSON")
        _global_config = Config.from_json(env) if env else Config.from_env()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg


def reset_config() -> None:
    """Drop the singleton so the next get_config() re-derives from the
    environment — called by shutdown() so a driver's ``_system_config``
    overrides do not leak into the process's next cluster."""
    global _global_config
    _global_config = None
