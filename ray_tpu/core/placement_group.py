"""Placement groups (reference: ``python/ray/util/placement_group.py`` —
``placement_group()`` :146, strategies PACK/SPREAD/STRICT_PACK/STRICT_SPREAD :18-19).

Bundles are reserved across node agents with 2-phase prepare/commit by the GCS PG
manager.  For TPU pods, bundle packing is ICI-topology-aware (SURVEY §2.3 row
"Placement/locality"): nodes carry ``tpu_slice``/``ici_coord`` labels;
multi-node PACK spills onto same-slice nodes nearest in ICI hops, and
STRICT_SPREAD selects the node set with minimal ICI diameter (a contiguous
sub-torus when one is free) — see ``scheduling.pack_bundles``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from .ids import PlacementGroupID
from .rpc import run_async

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self._placement: Optional[List[tuple]] = None

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self.bundles)

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def _gcs(self):
        from .core_worker import global_worker
        return global_worker().gcs

    def ready(self, timeout: float = 60.0) -> bool:
        if self._placement is not None:  # settled on the create reply
            return True
        info = run_async(self._gcs().call("wait_placement_group", pg_id=self.id,
                                          timeout=timeout, _timeout=timeout + 10))
        if info and info["state"] == "CREATED":
            self._placement = info["placement"]
            return True
        return False

    def wait(self, timeout_seconds: float = 60.0) -> bool:
        return self.ready(timeout_seconds)

    def bundle_placement(self) -> List[tuple]:
        """[(node_id_hex, agent_address)] per bundle."""
        if self._placement is None:
            if not self.ready():
                raise TimeoutError(f"placement group {self.id} not ready")
        return self._placement

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("bundles must be non-empty")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b}")
    from .core_worker import global_worker
    w = global_worker()
    pg_id = PlacementGroupID.from_random().hex()
    reply = run_async(w.gcs.call("create_placement_group", pg_id=pg_id,
                                 bundles=[dict(b) for b in bundles],
                                 strategy=strategy,
                                 name=name, lifetime=lifetime))
    pg = PlacementGroup(pg_id, bundles, strategy)
    info = reply.get("info") if isinstance(reply, dict) else None
    if info and info["state"] == "CREATED":
        pg._placement = info["placement"]
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    from .core_worker import global_worker
    run_async(global_worker().gcs.call("remove_placement_group", pg_id=pg.id))


def placement_group_table(pg: Optional[PlacementGroup] = None):
    from .core_worker import global_worker
    g = global_worker().gcs
    if pg is not None:
        return run_async(g.call("get_placement_group", pg_id=pg.id))
    return run_async(g.call("list_placement_groups"))
