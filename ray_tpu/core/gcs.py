"""GCS-equivalent cluster control plane.

One per cluster, like the reference's GCS server (``src/ray/gcs/gcs_server/gcs_server.h:79``,
subsystems initialized at :120-177).  Owns:

* **Node table + health checks** — agents register and heartbeat; missed heartbeats past
  the failure threshold mark the node dead and publish it (reference:
  ``GcsNodeManager`` + ``GcsHealthCheckManager``).
* **Internal KV** — namespaced key/value store; also backs the function registry
  (reference: ``GcsKvManager`` / ``function_manager.py`` shipping pickled defs via KV).
* **Actor manager** — registration, placement via a node agent lease, restart-on-failure
  up to ``max_restarts``, named/detached actors (reference: ``GcsActorManager``
  ``gcs_actor_manager.cc:246,632`` + ``GcsActorScheduler``).
* **Placement groups** — 2-phase prepare/commit bundle reservation across agents
  (reference: ``GcsPlacementGroupScheduler``, ``node_manager.proto:388-395``).
* **Pubsub** — long-lived subscriber connections receive one-way pushes per topic
  (reference: ``src/ray/pubsub/``).
* **Resource view broadcast** — aggregates agent heartbeats into the cluster view that
  drives client-side scheduling (reference: RaySyncer gossip, ``ray_syncer.h:86``).
* **Job table** and a bounded **task-event buffer** for the state API (reference:
  ``GcsJobManager`` / ``GcsTaskManager``).

State is optionally snapshotted to disk so a restarted GCS can recover cluster metadata
(reference: Redis-backed ``gcs_table_storage.cc``).
"""

from __future__ import annotations

import asyncio
import bisect
import os
import pickle
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import sched_explain
from .common import TaskSpec
from .config import get_config
from .ids import ActorID, JobID, NodeID, PlacementGroupID
from .rpc import ClientPool, RpcServer
from .sched_explain import PendingReason
from .scheduling import NodeView, pack_bundles, pick_node
from .sharded_table import SecondaryIndex, ShardedTable


class GcsServer:
    """The control-plane ROUTER: owns everything that needs global
    ordering (node table, jobs, actor registration + scheduling, PG 2PC,
    pubsub seq space) and fronts the optional GCS shard processes
    (``gcs_shard_processes > 0``, core/gcs_shard.py) that serve the hot
    key-partitionable traffic.  With shards enabled, shard-routable
    handlers here PROXY to the owning shard — so legacy clients keep
    working — while shard-aware clients (core/gcs_router.ShardedGcsClient)
    go client->shard direct by key."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persistence_path: Optional[str] = None,
                 session_dir: Optional[str] = None):
        self.server = RpcServer(self, host, port)
        cfg = get_config()
        self.session_dir = session_dir
        # shard-process plane (started in start() when configured)
        self._shard_procs: List = []          # Popen per shard index
        self._shard_addrs: List[str] = []
        self._shard_clients: List = []        # RpcClient per shard index
        self._shard_map_version = 0
        self.nodes: Dict[str, NodeView] = {}
        self.node_last_seen: Dict[str, float] = {}
        # Pubsub: PER-TOPIC seq-ordered logs (a poll for topic T touches
        # only T's log, cursor-indexed by bisect — never a scan of every
        # topic's traffic), fanned out once per loop tick (_fanout_tick).
        self._topic_logs: Dict[str, List[Tuple[int, dict]]] = {}
        self._event_seq = 0
        # parked pubsub polls: event -> the topic set it waits on.  Fanout
        # is TOPIC-AWARE: a tick's publishes wake only the subscribers of
        # the touched topics — waking every parked poll on every publish
        # made each control-plane transition (PG create, actor state) cost
        # an extra poll round trip per unrelated subscriber.
        self._event_waiters: Dict[asyncio.Event, frozenset] = {}
        self._fanout_topics: set = set()
        self._fanout_scheduled = False
        # Hot tables are hash-sharded (bounded rehash pauses, per-shard
        # iteration) with O(1)-maintained reverse indexes replacing every
        # failure-path full-table scan (see core/sharded_table.py).
        shards = max(1, cfg.gcs_table_shards)
        self.kv: ShardedTable = ShardedTable(shards)  # (ns, key) -> bytes
        self._kv_ns_index = SecondaryIndex()          # ns -> {key}
        self.actors: ShardedTable = ShardedTable(shards)  # actor hex -> info
        self._actors_by_node = SecondaryIndex()       # node_id -> {actor hex}
        self._live_actors_by_job = SecondaryIndex()   # job hex -> {actor hex}
        self.named_actors: Dict[Tuple[str, str], str] = {}  # (ns, name) -> actor id hex
        self.pgs: Dict[str, dict] = {}
        self._pg_events: Dict[str, asyncio.Event] = {}
        self.jobs: Dict[str, dict] = {}
        self.agent_clients = ClientPool()
        self.task_events: deque = deque(maxlen=cfg.task_events_max_buffer)
        #: events owners shed at their bounded buffers (observability)
        self.task_events_dropped = 0
        #: latest submission-plane counter snapshot per owner (piggybacks
        #: the task-event flush; sched_stats rolls these up)
        self.submit_plane_counters: Dict[str, dict] = {}
        # Scheduler explain plane: bounded ring of structured decision
        # records (pick_node/pack_bundles outcomes with per-node rejection
        # causes) from this GCS's own scheduling loops AND from owners
        # (add_sched_decisions piggybacks their task-event flush); plus
        # per-handler cumulative busy seconds when sched metrics are on.
        self.sched_decisions: deque = deque(
            maxlen=max(64, cfg.sched_decision_ring_len))
        # Object-plane flight recorder: bounded age-out ring of object
        # lifecycle transition events (CREATED/SEALED/SPILLED/RESTORED/
        # TRANSFERRED/RE_HOMED/FREED) flushed by node agents and owners —
        # the ``state.explain_object`` / ``raytpu explain <oid>`` backing
        # store (the sched_decision ring pattern on the data plane).
        self.object_events: deque = deque(
            maxlen=max(64, cfg.object_event_ring_len))
        self.object_events_dropped = 0
        # Health plane (util/health.py): bounded age-out ring of alert
        # transition events (raised/cleared) — the sched_decision ring
        # pattern applied to health.  The GCS evaluates its two
        # process-local rules at health-check cadence; the dashboard
        # head flushes its rule subset here so ``state.health()`` /
        # ``raytpu doctor`` see ONE trail regardless of who detected.
        self.health_alerts: deque = deque(
            maxlen=max(16, cfg.health_ring_len))
        self._health_detector = None
        self._health_prev: Dict[str, object] = {}
        #: latest active-alert list per external detector ("head"), with
        #: its push timestamp — stale pushes age out of handle_health
        self._health_active_ext: Dict[str, dict] = {}
        self._handler_busy: Dict[str, float] = {}
        self._handler_calls: Dict[str, int] = {}
        self._gcs_hist_keys: Dict[str, tuple] = {}  # precomputed tag keys
        # Runtime chaos control (core/chaos.py): the cluster-wide spec and
        # its version; agents learn of changes via heartbeat piggyback
        # (and anyone else via the "chaos" pubsub topic).
        self._chaos_spec: Optional[dict] = None
        self._chaos_version = 0
        # Elastic train plane: active drain notices (node agents report at
        # drain START, seconds before the node dies — the advance warning
        # elastic trainers resize on) and the bounded completed-resize
        # ring + in-progress map the doctor/state surfaces read back.
        self._drain_notices: Dict[str, dict] = {}
        self._train_resizes: deque = deque(maxlen=256)
        self._train_resizing: Dict[str, dict] = {}
        # Dead lease-owner broadcast: worker addresses whose process is
        # confirmed gone (actor killed/crashed, node died under it).  Agents
        # pick these up on heartbeat and reclaim any task-worker lease that
        # owner still holds — without this an orphaned lease pins CPUs until
        # the pin sweep's 3-strike liveness probe (~30s), which stalls an
        # elastic re-form racing the reclamation for the freed slot.
        self._dead_owner_seq = 0
        self._dead_owners: deque = deque(maxlen=256)
        self._job_counter = 0
        self._bg: List[asyncio.Task] = []
        self.persistence_path = persistence_path
        self._persist_scheduled = False  # coalesces _persist_soon per tick
        self._started_at = time.time()

    # ------------------------------------------------------------------ boot

    async def start(self):
        self._maybe_restore()
        if sched_explain.enabled():
            # per-handler busy attribution (synchronous-segment thread-CPU
            # time; see rpc._BusyTimed) — the "what is the control plane
            # spending its time on" half of the explain plane
            self.server.busy_cb = self._on_handler_busy
        await self.server.start()
        await self._start_shards()
        self._restart_pending_pgs()
        self._restart_pending_actors()
        self._bg.append(asyncio.ensure_future(self._health_check_loop()))

        async def _self_call(method, **kw):
            # the GCS writes its own distress events straight into its KV
            return await getattr(self, f"handle_{method}")(**kw)

        from ray_tpu.util.loop_monitor import install as _install_loop_mon
        self._loop_monitor = _install_loop_mon(
            asyncio.get_event_loop(), "gcs", gcs_call=_self_call)
        return self

    @property
    def address(self) -> str:
        return self.server.address

    async def stop(self):
        if getattr(self, "_loop_monitor", None):
            self._loop_monitor.stop()
        for t in self._bg:
            t.cancel()
        for c in self._shard_clients:
            try:
                await c.close()
            except Exception:
                pass
        for proc in self._shard_procs:
            try:
                proc.terminate()
            except Exception:
                pass

        def _reap(procs=list(self._shard_procs)):
            # blocking waits belong OFF the loop: a shard wedged in a
            # synchronous snapshot write must not freeze every other
            # coroutine here for its grace period
            for proc in procs:
                try:
                    proc.wait(timeout=5)
                except Exception:
                    try:
                        proc.kill()
                    except Exception:
                        pass

        if self._shard_procs:
            await asyncio.get_event_loop().run_in_executor(None, _reap)
        await self.agent_clients.close_all()
        await self.server.stop()

    # ------------------------------------------------------- shard processes

    @property
    def num_shards(self) -> int:
        return len(self._shard_addrs)

    async def _start_shards(self):
        n = get_config().gcs_shard_processes
        if n <= 0:
            return
        from .gcs_shard import spawn_shard_processes
        from .rpc import RpcClient
        # Shards ALWAYS get a snapshot file when any directory exists to
        # put one in: a supervised shard respawn must restore its slice of
        # the KV (function registry, workflow commits) even when the
        # router itself runs without persistence — a single-process GCS
        # only loses its KV by dying wholesale, and sharding must not
        # weaken that.
        self._shard_persist_base = self.persistence_path or (
            os.path.join(self.session_dir, "gcs.snap")
            if self.session_dir else None)
        # subprocess spawn + the stdout handshake block; keep the loop live
        spawned = await asyncio.get_event_loop().run_in_executor(
            None, spawn_shard_processes, n, self._shard_persist_base,
            self.session_dir)
        self._shard_procs = [p for p, _a in spawned]
        self._shard_addrs = [a for _p, a in spawned]
        self._shard_clients = [RpcClient(a) for a in self._shard_addrs]
        self._shard_map_version += 1
        self._bg.append(asyncio.ensure_future(self._shard_watch_loop()))

    async def _shard_watch_loop(self):
        """Shard supervision: a dead shard process is respawned at the
        same index, restoring from its own snapshot file — the router is
        the shard fleet's supervisor the way an agent supervises its
        workers.  Clients holding the stale address fail fast with
        ConnectionLost and fall back to the router proxy until they
        refresh the map (heartbeat piggyback / get_shard_map)."""
        from .gcs_shard import spawn_shard_processes
        from .rpc import RpcClient
        while True:
            await asyncio.sleep(0.5)
            for i, proc in enumerate(self._shard_procs):
                if proc.poll() is None:
                    continue
                try:
                    spawned = await asyncio.get_event_loop().run_in_executor(
                        None, spawn_shard_processes,
                        len(self._shard_procs), self._shard_persist_base,
                        self.session_dir, i)
                except Exception:
                    continue
                (newproc, addr), = spawned
                try:
                    await self._shard_clients[i].close()
                except Exception:
                    pass
                self._shard_procs[i] = newproc
                self._shard_addrs[i] = addr
                self._shard_clients[i] = RpcClient(addr)
                self._shard_map_version += 1
                self._publish("gcs_shards",
                              {"version": self._shard_map_version,
                               "shards": list(self._shard_addrs)})

    async def handle_get_shard_map(self):
        """Shard address list for shard-aware clients (gcs_router
        facade).  Empty when sharding is off — the facade then routes
        everything here."""
        return {"version": self._shard_map_version,
                "shards": list(self._shard_addrs)}

    def _shard_client_for(self, key: str):
        """Proxy-side shard pick — THE partition helper, same as clients."""
        from .gcs_router import shard_index
        return self._shard_clients[shard_index(key, len(self._shard_clients))]

    async def _shard_call(self, shard_key: str, method: str,
                          _idempotent: bool = True, **kwargs):
        """Proxy one call to the shard owning ``shard_key``, riding
        through a shard-process restart: transport failures re-resolve
        the CURRENT client (the supervisor swaps in the replacement's
        address) and retry until the standard call deadline — a shard
        respawn costs proxied callers latency, never an error."""
        from .rpc import RemoteError, RpcError
        deadline = time.monotonic() + get_config().rpc_call_timeout_s
        while True:
            client = self._shard_client_for(shard_key)
            try:
                return await client.call_retry(
                    method, _idempotent=_idempotent,
                    _timeout=max(1.0, deadline - time.monotonic()), **kwargs)
            except RemoteError:
                raise  # application error from the shard handler
            except (ConnectionError, OSError, RpcError,
                    asyncio.TimeoutError):
                if time.monotonic() >= deadline:
                    raise
                await asyncio.sleep(0.1)

    async def _shard_call_all(self, method: str, **kwargs) -> List:
        """Fan one read out to every shard; unreachable shards contribute
        nothing (their supervisor is already respawning them)."""
        if not self._shard_clients:
            return []

        async def _one(c):
            try:
                return await c.call(method, _timeout=10, **kwargs)
            except Exception:
                return None

        return [r for r in await asyncio.gather(
            *[_one(c) for c in self._shard_clients]) if r is not None]

    # ------------------------------------------------------------- persistence

    def _maybe_restore(self):
        p = self.persistence_path
        if p and os.path.exists(p):
            with open(p, "rb") as f:
                snap = pickle.load(f)
            # Sharded tables restore entry-by-entry (the snapshot stores
            # plain dicts, not shard layouts, so gcs_table_shards may
            # change between incarnations) and their secondary indexes are
            # REBUILT from the restored rows — the indexes are derived
            # state, never independently authoritative.
            for k, v in snap.get("kv", {}).items():
                self.kv[k] = v
                self._kv_ns_index.add(k[0], k[1])
            self.jobs = snap.get("jobs", {})
            self.named_actors = snap.get("named_actors", {})
            for aid, info in snap.get("actors", {}).items():
                self.actors[aid] = info
                self._index_actor(aid, info)
            self._job_counter = snap.get("job_counter", 0)
            # pubsub topic logs + global seq: subscriber cursors from the
            # previous incarnation stay valid (a poll after restart picks
            # up exactly where it left off instead of replaying or
            # skipping the world)
            self._topic_logs = {t: [tuple(e) for e in log] for t, log in
                                snap.get("topic_logs", {}).items()}
            self._event_seq = snap.get("event_seq", 0)
            # placement groups: CREATED placements restore as-is (their
            # nodes re-register); PENDING ones get their scheduler kicked
            # again once the loop runs
            self.pgs = snap.get("pgs", {})
            self._chaos_spec = snap.get("chaos_spec")
            self._chaos_version = snap.get("chaos_version", 0)
            for pg_id, info in self.pgs.items():
                self._pg_events[pg_id] = asyncio.Event()
                if info.get("state") in ("CREATED", "INFEASIBLE", "REMOVED"):
                    self._pg_events[pg_id].set()

    def _restart_pending_pgs(self):
        for pg_id, info in self.pgs.items():
            if info.get("state") == "PENDING":
                asyncio.ensure_future(self._schedule_pg(pg_id))

    def _restart_pending_actors(self):
        """Re-kick scheduling for actors snapshotted mid-placement: the
        in-flight _schedule_actor task died with the previous process, and
        nothing else ever unsticks a PENDING/RESTARTING actor (the
        report_actor_death path early-returns on RESTARTING)."""
        for aid, info in list(self.actors.items()):
            if (info.get("state") in ("PENDING", "RESTARTING")
                    and info.get("spec") is not None):
                asyncio.ensure_future(self._schedule_actor(aid))

    def _persist_soon(self):
        """Coalesced snapshot write: transitions that are NOT a durability
        contract (actor/PG state — recoverable from re-registration and
        owner retries) schedule ONE full-state write per loop tick instead
        of pickling the whole GCS per event.  A 1000-actor wave costs one
        snapshot, not 2-3 per actor.  KV/job writes stay synchronous: a
        workflow step's commit must be on disk before its kv_put acks."""
        if not self.persistence_path or self._persist_scheduled:
            return
        self._persist_scheduled = True

        def _flush():
            self._persist_scheduled = False
            self._persist()

        try:
            asyncio.get_running_loop().call_soon(_flush)
        except RuntimeError:
            _flush()  # no loop (unit tests): write inline

    def _persist(self):
        p = self.persistence_path
        if not p:
            return
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"kv": self.kv.to_dict(), "jobs": self.jobs,
                         "named_actors": self.named_actors,
                         "actors": self.actors.to_dict(),
                         "job_counter": self._job_counter,
                         "pgs": self.pgs,
                         "topic_logs": self._topic_logs,
                         "event_seq": self._event_seq,
                         "chaos_spec": self._chaos_spec,
                         "chaos_version": self._chaos_version}, f)
        os.replace(tmp, p)

    # ------------------------------------------------------- actor indexes

    def _index_actor(self, aid: str, info: dict):
        """(Re)derive one actor's index membership from its info dict —
        used on restore; live transitions maintain the indexes in place."""
        if info.get("state") == "DEAD":
            return
        self._actors_by_node.add(info.get("node_id"), aid)
        self._live_actors_by_job.add(info.get("job_id"), aid)

    def _actor_placed(self, aid: str, info: dict, node_id: str):
        self._actors_by_node.move(info.get("node_id"), node_id, aid)

    def _actor_unplaced(self, aid: str, info: dict):
        self._actors_by_node.discard(info.get("node_id"), aid)

    def _actor_dead(self, aid: str, info: dict):
        self._actors_by_node.discard(info.get("node_id"), aid)
        self._live_actors_by_job.discard(info.get("job_id"), aid)

    def _note_dead_owner(self, addr: Optional[str]):
        """Record a confirmed-dead worker address for heartbeat broadcast
        (see _dead_owners above).  seq-tagged so each agent only replays
        entries it has not seen; the deque bound means an agent that falls
        >256 entries behind misses some — the pin sweep backstops those."""
        if not addr:
            return
        self._dead_owner_seq += 1
        self._dead_owners.append((self._dead_owner_seq, addr))

    # ---------------------------------------------------------------- pubsub
    #
    # Long-poll pubsub (reference: GCS pubsub long-polling,
    # ``core_worker.proto:436-441``): subscribers call ``pubsub_poll`` with a
    # cursor; the call parks until an event past the cursor arrives for one of
    # the requested topics.

    def _publish(self, topic: str, payload: dict):
        self._event_seq += 1
        log = self._topic_logs.setdefault(topic, [])
        log.append((self._event_seq, payload))
        cap = max(100, get_config().gcs_pubsub_topic_log_len)
        if len(log) > cap:
            # trim front half: cursors are global seqs, so a subscriber
            # that fell further behind simply misses the trimmed window
            # (same contract the old global ring had)
            del log[:len(log) // 2]
        # Fanout is BATCHED per loop tick: a burst of N publishes in one
        # tick (an actor wave, a node death cascade) wakes each parked
        # subscriber once, not N times — wake cost is O(subscribers) per
        # tick instead of O(subscribers x publishes).
        self._fanout_topics.add(topic)
        if not self._fanout_scheduled:
            self._fanout_scheduled = True
            try:
                # get_running_loop (not get_event_loop): with no RUNNING
                # loop the latter hands back a fresh dead loop on 3.10,
                # the callback never fires, and the latched flag would
                # suppress every future wakeup
                asyncio.get_running_loop().call_soon(self._fanout_tick)
            except RuntimeError:
                self._fanout_tick()  # no loop (unit tests): wake inline

    def _fanout_tick(self):
        self._fanout_scheduled = False
        touched = self._fanout_topics
        self._fanout_topics = set()
        for ev, topics in self._event_waiters.items():
            if not topics.isdisjoint(touched):
                ev.set()

    async def handle_publish(self, topic: str, payload: dict):
        """Generic topic publish (reference: src/ray/pubsub Publisher) — used
        by the log monitor, available to any client."""
        self._publish(topic, payload)
        return self._event_seq

    async def handle_pubsub_poll(self, topics: List[str], cursor: int,
                                 timeout: float = 30.0):
        def pending():
            # Cursor-indexed per-topic reads: bisect each requested topic's
            # log past the cursor and merge by seq — cost is O(new events
            # for THESE topics), flat in total cluster traffic.
            out: List[Tuple[int, str, dict]] = []
            for t in topics:
                log = self._topic_logs.get(t)
                if not log:
                    continue
                i = bisect.bisect_right(log, cursor, key=lambda e: e[0])
                out.extend((seq, t, p) for seq, p in log[i:])
            out.sort(key=lambda e: e[0])
            return out

        got = pending()
        if got:
            return self._event_seq, got
        ev = asyncio.Event()
        self._event_waiters[ev] = frozenset(topics)
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        finally:
            self._event_waiters.pop(ev, None)
        return self._event_seq, pending()

    # ---------------------------------------------------------------- chaos
    #
    # Runtime control of the fault-injection plane (core/chaos.py).  A
    # chaos_set installs the spec in THIS process, bumps the version, and
    # broadcasts on the "chaos" pubsub topic; agents additionally converge
    # via heartbeat piggyback (handle_heartbeat) and forward to their
    # workers — so one call degrades every link in the cluster.

    async def handle_chaos_set(self, spec: dict | str | None):
        from . import chaos as _chaos
        if isinstance(spec, str):
            import json as _json
            spec = _json.loads(spec) if spec.strip() else {}
        self._chaos_version += 1
        self._chaos_spec = spec or None
        _chaos.install(spec)
        self._publish("chaos", {"version": self._chaos_version,
                                "spec": self._chaos_spec})
        return self._chaos_version

    async def handle_chaos_clear(self):
        return await self.handle_chaos_set(None)

    async def handle_chaos_get(self):
        from . import chaos as _chaos
        inj = _chaos.injector()
        return {"version": self._chaos_version, "spec": self._chaos_spec,
                "injected": inj.injected_counts() if inj else {}}

    # ---------------------------------------------------------------- nodes

    async def handle_register_node(self, node_id: str, address: str,
                                   resources: Dict[str, float],
                                   labels: Dict[str, str]):
        self.nodes[node_id] = NodeView(node_id, address, dict(resources),
                                       dict(resources), labels, True, 0)
        self.node_last_seen[node_id] = time.monotonic()
        self._publish("nodes", {"event": "alive", "node_id": node_id, "address": address})
        return {"node_id": node_id, "cluster_view": self._view_payload(),
                "shard_map": {"version": self._shard_map_version,
                              "shards": list(self._shard_addrs)},
                # the dead-owner broadcast seq is in-memory: after a GCS
                # restart it re-counts from 0, below any seq the agents
                # remember, and the `seq < ours` heartbeat check would
                # silently skip every new broadcast until it caught up.
                # Re-registration (the unknown-node heartbeat path) is
                # exactly when an agent meets a restarted GCS — hand it
                # the current seq so it resyncs instead of comparing
                # against a counter from a previous incarnation.
                "dead_owners_seq": self._dead_owner_seq}

    async def handle_update_node_resources(self, node_id: str,
                                           total: Dict[str, float],
                                           available: Dict[str, float]):
        """A node's resource CAPACITY changed at runtime (reference:
        experimental/dynamic_resources.py -> NodeManager resource-set
        path): refresh the view totals so the scheduler and autoscaler
        see the new shape immediately instead of at the next heartbeat."""
        n = self.nodes.get(node_id)
        if n is None:
            return {"unknown": True}
        n.total = dict(total)
        n.available = dict(available)
        self._publish("nodes", {"event": "resources", "node_id": node_id,
                                "total": n.total})
        return {"ok": True}

    async def handle_heartbeat(self, node_id: str, available: Dict[str, float],
                               queue_len: int = 0, store_stats: dict | None = None,
                               queued_demands: List[Dict[str, float]] | None = None,
                               total: Dict[str, float] | None = None,
                               chaos_version: int | None = None,
                               draining: bool = False,
                               shard_map_version: int | None = None,
                               dead_owners_seq: int | None = None,
                               task_leased: Dict[str, float] | None = None):
        n = self.nodes.get(node_id)
        if n is None:
            return {"unknown": True}  # agent should re-register
        n.available = dict(available)
        # short-lived task-lease usage: elastic sizing treats it as
        # reclaimable headroom (the leases idle-return within seconds once
        # their submitter stops), unlike actor/bundle holds
        n.task_leased = dict(task_leased or {})
        if total is not None:
            n.total = dict(total)
        n.queue_len = queue_len
        if bool(draining) != n.draining:
            n.draining = bool(draining)
            if n.draining:
                # broadcast the notice: schedulers route around the node
                # while it finishes leases and re-homes its objects
                self._publish("nodes", {"event": "draining",
                                        "node_id": node_id})
        # resource shapes queued behind this node's leases — the autoscaler's
        # scale-up signal (reference: cluster load reported to the monitor,
        # autoscaler/_private/load_metrics.py)
        n.labels["_queued_demands"] = queued_demands or []
        if not n.alive:
            n.alive = True
            self._publish("nodes", {"event": "alive", "node_id": node_id,
                                    "address": n.address})
        if store_stats:
            n.labels["_store"] = store_stats
        self.node_last_seen[node_id] = time.monotonic()
        res = {"view": self._view_payload()}
        if chaos_version is not None and chaos_version != self._chaos_version:
            # piggyback the runtime chaos spec on the reply so agents that
            # missed the pubsub broadcast (or restarted) converge anyway
            res["chaos"] = {"version": self._chaos_version,
                            "spec": self._chaos_spec}
        if (shard_map_version is not None
                and shard_map_version != self._shard_map_version):
            # same convergence pattern for the shard map: a respawned
            # shard's new address reaches every agent within a heartbeat
            res["shard_map"] = {"version": self._shard_map_version,
                                "shards": list(self._shard_addrs)}
        if (dead_owners_seq is not None
                and dead_owners_seq < self._dead_owner_seq):
            # confirmed-dead lease owners this agent has not yet replayed:
            # it reclaims their leased task workers on receipt (the ~30s
            # pin-sweep probe remains the backstop for owners the GCS
            # never tracked, e.g. a SIGKILLed driver)
            res["dead_owners"] = {
                "seq": self._dead_owner_seq,
                "addrs": [a for s, a in self._dead_owners
                          if s > dead_owners_seq]}
        return res

    async def handle_drain_node(self, node_id: str):
        await self._mark_node_dead(node_id, reason="drained")
        return True

    async def handle_report_drain_notice(self, node_id: str,
                                         notice_s: float = 0.0):
        """A node agent received a preemption notice and started draining
        — recorded at drain START so elastic trainers (and the doctor)
        see the warning while the notice window is still open, not after
        the node is gone.  Also flips the node's draining flag
        immediately: waiting one heartbeat to route schedulers around a
        dying node wastes notice budget."""
        now = time.time()
        self._drain_notices[node_id] = {
            "node_id": node_id, "notice_s": float(notice_s),
            "reported_at": now, "deadline": now + max(0.0, float(notice_s)),
        }
        n = self.nodes.get(node_id)
        if n is not None and not n.draining:
            n.draining = True
            self._publish("nodes", {"event": "draining",
                                    "node_id": node_id})
        return True

    async def handle_get_drain_notices(self):
        """Active + recently-completed drain notices.  ``active`` means
        the node is still alive (draining); a notice lingers ~60s past
        its node's death so doctor/timeline surfaces can attribute the
        death to the drain, then ages out."""
        now = time.time()
        out = []
        for nid, rec in list(self._drain_notices.items()):
            n = self.nodes.get(nid)
            alive = bool(n is not None and n.alive)
            if now - rec["deadline"] > 60.0:
                if not alive:
                    self._drain_notices.pop(nid, None)
                    continue
                if n is not None and not n.draining:
                    # drain aborted (preemption cancelled): the node
                    # outlived its deadline by the full grace window and
                    # cleared its draining flag — without this the notice
                    # stays active forever and doctor shows a phantom
                    # "draining ... expires in 0s" for a healthy node
                    self._drain_notices.pop(nid, None)
                    continue
            out.append({**rec, "active": alive,
                        "remaining_s": max(0.0, rec["deadline"] - now)})
        return out

    async def handle_train_resize_started(self, trial: str, record: dict):
        self._train_resizing[trial or "train"] = {
            **(record or {}), "ts": time.time()}
        return True

    async def handle_add_train_resize(self, record: dict):
        """One completed elastic resize (direction/from/to/wall_s/...) —
        appended to the bounded ring behind ``raytpu train`` / doctor."""
        trial = (record or {}).get("trial") or "train"
        self._train_resizing.pop(trial, None)
        self._train_resizes.append(dict(record or {}))
        self._publish("train", {"event": "resize", **(record or {})})
        return True

    async def handle_get_train_resizes(self, limit: int = 100):
        # an in-progress entry older than 5 min is a dead driver, not a
        # resize — age it out rather than alarming forever
        now = time.time()
        for t, rec in list(self._train_resizing.items()):
            if now - rec.get("ts", now) > 300.0:
                self._train_resizing.pop(t, None)
        return {"records": list(self._train_resizes)[-max(1, int(limit)):],
                "in_progress": dict(self._train_resizing)}

    async def handle_report_pending_demand(self, reporter: str, shape: dict,
                                           count: int = 1):
        """Drivers/workers report demand shapes no live node can satisfy
        (infeasible-task load; reference: load_metrics resource demand).
        Entries expire after a few seconds of silence."""
        if not hasattr(self, "_pending_demands"):
            self._pending_demands = {}
        key = (reporter, tuple(sorted(shape.items())))
        self._pending_demands[key] = (dict(shape), count, time.monotonic())
        return True

    async def handle_get_load(self):
        """Cluster load for the autoscaler: per-node resources + pending
        demand shapes + infeasible driver demands (reference: the monitor's
        GetAllResourceUsage poll)."""
        now = time.monotonic()
        pending = []
        for key, (shape, count, ts) in list(
                getattr(self, "_pending_demands", {}).items()):
            if now - ts > 5.0:
                self._pending_demands.pop(key, None)
                continue
            pending.extend([shape] * count)
        return {
            "nodes": {
                nid: {
                    "alive": n.alive,
                    # a draining node's free capacity is a mirage — the
                    # autoscaler must not let it absorb simulated demand
                    # (its replacement IS the demand)
                    "draining": n.draining,
                    "total": n.total,
                    "available": n.available,
                    "queue_len": n.queue_len,
                    "queued_demands": n.labels.get("_queued_demands", []),
                    "labels": {k: v for k, v in n.labels.items()
                               if not k.startswith("_")},
                }
                for nid, n in self.nodes.items()},
            "pending_demands": pending,
        }

    def _view_payload(self) -> Dict[str, dict]:
        return {nid: {"address": n.address, "total": n.total,
                      "available": n.available, "labels": {k: v for k, v in n.labels.items()
                                                           if not k.startswith("_")},
                      "alive": n.alive, "queue_len": n.queue_len,
                      "draining": n.draining, "task_leased": n.task_leased}
                for nid, n in self.nodes.items()}

    async def handle_get_cluster_view(self):
        return self._view_payload()

    async def _health_check_loop(self):
        cfg = get_config()
        while True:
            await asyncio.sleep(cfg.health_check_period_s)
            now = time.monotonic()
            deadline = cfg.health_check_period_s * cfg.health_check_failure_threshold
            for nid, n in list(self.nodes.items()):
                if n.alive and now - self.node_last_seen.get(nid, now) > deadline:
                    await self._mark_node_dead(nid, reason="heartbeat timeout")
            try:
                self._health_tick()
            except Exception:
                pass  # the detector must never take down liveness checks

    def _health_tick(self):
        """GCS-side health rules (EVENTS_SHED, GCS_HANDLER_HOT) over
        process-local counters this object already maintains — dict
        walks at health-check cadence, no RPCs, no per-task work.  With
        the kill switch off this is ONE boolean check."""
        from ray_tpu.util import health as health_plane
        if not health_plane.enabled():
            self._health_detector = None  # next enable starts clean
            return
        now = time.time()
        det = self._health_detector
        if det is None:
            # first enabled tick: baseline the cumulative counters so
            # pre-existing sheds don't fire a stale alert
            self._health_detector = health_plane.gcs_detector()
            self._health_prev = {"ts": now,
                                 "shed": self.task_events_dropped,
                                 "busy": dict(self._handler_busy)}
            return
        prev = self._health_prev
        dt = max(1e-9, now - float(prev.get("ts", now)))
        shed_delta = self.task_events_dropped - int(prev.get("shed", 0))
        prev_busy = prev.get("busy") or {}
        busy_frac = {}
        for method, busy in self._handler_busy.items():
            d = busy - prev_busy.get(method, 0.0)
            if d > 0:
                busy_frac[method] = d / dt
        self._health_prev = {"ts": now, "shed": self.task_events_dropped,
                             "busy": dict(self._handler_busy)}
        snap = {"now": now, "events_shed": max(0, shed_delta),
                "events_shed_total": self.task_events_dropped,
                "handler_busy": busy_frac,
                # elastic evidence: nodes draining under an active notice
                # and trains mid-resize — NODE_DRAINING / TRAIN_RESIZING
                # fire from here so an operator can tell planned churn
                # from flapping
                "draining_notices": {
                    nid: max(0.0, rec["deadline"] - time.time())
                    for nid, rec in self._drain_notices.items()
                    if (self.nodes.get(nid) is not None
                        and self.nodes[nid].alive)},
                "train_resizing": {
                    t: {"direction": rec.get("direction"),
                        "from": rec.get("from")}
                    for t, rec in self._train_resizing.items()}}
        events = det.observe(snap, now)
        health_plane.record_transitions(events, det)
        if events:
            self._prune_health_alerts()
            self.health_alerts.extend(events)

    async def _mark_node_dead(self, node_id: str, reason: str):
        n = self.nodes.get(node_id)
        if n is None or not n.alive:
            return
        n.alive = False
        self._publish("nodes", {"event": "dead", "node_id": node_id, "reason": reason})
        # Restart or fail actors that lived there (reference:
        # GcsActorManager::OnNodeDead) — via the by-node index, so a node
        # death touches only ITS actors, not the whole table.
        for aid in self._actors_by_node.get(node_id):
            info = self.actors.get(aid)
            if info is not None and info["state"] in ("ALIVE", "PENDING"):
                await self._on_actor_failure(aid, f"node {node_id[:12]} died: {reason}")

    # ------------------------------------------------------------------- KV

    # With shard processes enabled, the KV lives IN the shards (by
    # namespace); these handlers become the compat PROXY for clients that
    # don't hold the shard map — shard-aware clients skip the hop.

    async def handle_kv_put(self, ns: str, key: str, value: bytes,
                            overwrite: bool = True):
        if self._shard_clients:
            return await self._shard_call(
                ns, "kv_put", ns=ns, key=key, value=value,
                overwrite=overwrite)
        k = (ns, key)
        if not overwrite and k in self.kv:
            return False
        self.kv[k] = value
        self._kv_ns_index.add(ns, key)
        self._persist()
        return True

    async def handle_kv_get(self, ns: str, key: str):
        if self._shard_clients:
            return await self._shard_call(ns, "kv_get", ns=ns, key=key,
                                          _idempotent=False)
        return self.kv.get((ns, key))

    async def handle_kv_multi_get(self, ns: str, keys: List[str]):
        if self._shard_clients:
            return await self._shard_call(ns, "kv_multi_get", ns=ns,
                                          keys=keys, _idempotent=False)
        return {k: self.kv[(ns, k)] for k in keys if (ns, k) in self.kv}

    async def handle_kv_del(self, ns: str, key: str):
        if self._shard_clients:
            return await self._shard_call(ns, "kv_del", ns=ns, key=key)
        existed = self.kv.pop((ns, key), None) is not None
        if existed:
            self._kv_ns_index.discard(ns, key)
            self._persist()
        return existed

    async def handle_kv_keys(self, ns: str, prefix: str = ""):
        if self._shard_clients:
            return await self._shard_call(ns, "kv_keys", ns=ns, prefix=prefix,
                                          _idempotent=False)
        # per-namespace index: listing one ns never scans the others
        return [k for k in self._kv_ns_index.get(ns) if k.startswith(prefix)]

    async def handle_kv_exists(self, ns: str, key: str):
        if self._shard_clients:
            return await self._shard_call(ns, "kv_exists", ns=ns, key=key,
                                          _idempotent=False)
        return (ns, key) in self.kv

    # ---------------------------------------------------------------- actors

    async def handle_register_actor(self, spec: TaskSpec,
                                    get_if_exists: bool = False):
        """Register (or, with get_if_exists, atomically adopt) an actor.

        The GCS is the single serialization point for names: concurrent
        get-or-create callers race HERE, not at a client-side pre-check, so
        the loser receives the winner's actor id (reference:
        GcsActorManager name-conflict handling for get_if_exists)."""
        aid = spec.actor_id.hex()
        if spec.actor_name:
            key = (spec.namespace or "default", spec.actor_name)
            if key in self.named_actors:
                existing = self.named_actors[key]
                if self.actors.get(existing, {}).get("state") != "DEAD":
                    if get_if_exists:
                        return existing
                    raise ValueError(f"actor name {spec.actor_name!r} already taken")
            self.named_actors[key] = aid
        self.actors[aid] = {
            "actor_id": aid, "state": "PENDING", "spec": spec, "address": None,
            "node_id": None, "restarts_left": spec.max_restarts, "name": spec.actor_name,
            "namespace": spec.namespace or "default", "owner": spec.owner,
            "death_cause": None, "num_restarts": 0, "class_name": spec.name,
            "lifetime": spec.lifetime, "job_id": spec.job_id.hex(),
        }
        self._live_actors_by_job.add(spec.job_id.hex(), aid)
        self._persist_soon()
        asyncio.ensure_future(self._schedule_actor(aid))
        return aid

    async def _schedule_actor(self, aid: str, delay: float = 0.0):
        if delay:
            await asyncio.sleep(delay)
        info = self.actors.get(aid)
        if info is None or info["state"] == "DEAD":
            return
        spec: TaskSpec = info["spec"]
        pg_pending = False
        last_reason = None
        for attempt in range(120):
            # Re-check each attempt: a kill while PENDING/RESTARTING must not be
            # overwritten back to ALIVE by a late placement success.
            if self.actors.get(aid) is not info or info["state"] == "DEAD":
                return
            strategy = spec.scheduling_strategy
            pg_pending = False
            if (isinstance(strategy, tuple) and strategy
                    and strategy[0] == "_pg"):
                # PG-placed actor: the creation MUST go to the node holding
                # its bundle — pick_node knows nothing about the resolved
                # ("_pg", pg_id, idx, node_id) tuple and used to fall
                # through to the DEFAULT policy, sending create_actor to an
                # arbitrary node whose agent then raised "unknown placement
                # bundle" (placement succeeded only by retry luck).  The
                # PG table's CURRENT placement wins over the node recorded
                # at submission (a rescheduled PG may have moved).
                from .scheduling import NodeAffinitySchedulingStrategy
                _tag, pg_id, idx, nid_hint = strategy
                pg = self.pgs.get(pg_id)
                target = None
                placement = (pg or {}).get("placement")
                if placement and 0 <= idx < len(placement):
                    target = placement[idx][0]
                # a missing/uncreated placement means the actor is blocked
                # behind its placement group, not behind resources
                pg_pending = target is None and (
                    pg is None or pg.get("state") != "CREATED")
                strategy = NodeAffinitySchedulingStrategy(
                    target or nid_hint, soft=False)
            explain: Dict[str, object] = {}
            nid = pick_node(self.nodes, spec.resources, strategy,
                            explain=explain)
            if nid is None:
                reason = (PendingReason.PG_PENDING if pg_pending
                          else sched_explain.reason_for_no_node(explain))
                if info.get("pending_reason") != reason:
                    info["pending_reason"] = reason
                    info["reason_since"] = time.time()
                # decision records are rate-limited to transitions + a
                # periodic heartbeat: a stuck actor's 120-attempt loop
                # must not flood the ring with identical records
                if reason != last_reason or attempt % 20 == 0:
                    last_reason = reason
                    self._record_decision({
                        "kind": "actor", "id": aid,
                        "label": info.get("class_name"),
                        "demand": dict(spec.resources or {}),
                        "outcome": "no_node", "reason": reason,
                        "candidates": explain.get("candidates"),
                        **sched_explain.bound_rejected(
                            explain.get("rejected")),
                        "attempt": attempt})
            if nid is not None:
                agent = self.agent_clients.get(self.nodes[nid].address)
                try:
                    # Idempotent retry: a creation whose REPLY was lost must
                    # hand back the same worker on retry, not lease a second
                    # one (the agent's dedup window holds the grant).
                    res = await agent.call_retry(
                        "create_actor", spec=spec,
                        _timeout=get_config().actor_creation_timeout_s + 30)
                    if self.actors.get(aid) is not info or info["state"] == "DEAD":
                        # Killed while the creation RPC was in flight: reap the
                        # freshly created worker instead of resurrecting.
                        try:
                            await agent.call_retry(
                                "kill_worker", worker_id=res["worker_id"],
                                reason="actor killed during creation")
                        except Exception:
                            pass
                        return
                    self._actor_placed(aid, info, nid)
                    info.pop("pending_reason", None)
                    info.pop("reason_since", None)
                    info.update(state="ALIVE", address=res["worker_address"],
                                node_id=nid, worker_id=res["worker_id"])
                    if last_reason is not None or attempt > 0:
                        # close a previously-stuck trail; happy-path
                        # placements stay out of the ring (actor churn
                        # would evict the records worth keeping)
                        self._record_decision({
                            "kind": "actor", "id": aid,
                            "label": info.get("class_name"),
                            "outcome": "placed", "node": nid,
                            "attempt": attempt})
                    self._persist_soon()
                    self._publish("actors", {"actor_id": aid, "state": "ALIVE",
                                             "address": res["worker_address"]})
                    return
                except Exception as e:  # noqa: BLE001 — placement failure, retry
                    info["last_error"] = repr(e)
            await asyncio.sleep(0.25)
        await self._fail_actor(aid, f"could not place actor: {info.get('last_error')}")

    async def _on_actor_failure(self, aid: str, reason: str):
        info = self.actors.get(aid)
        # RESTARTING guard: the worker-death report that follows a deliberate
        # restart-kill must not burn a second restart.
        if info is None or info["state"] in ("DEAD", "RESTARTING"):
            return
        if info["restarts_left"] != 0:
            if info["restarts_left"] > 0:
                info["restarts_left"] -= 1
            info["num_restarts"] += 1
            self._actor_unplaced(aid, info)
            # the pre-restart incarnation's process is gone: any task-worker
            # lease it still owns is orphaned — broadcast before the address
            # is cleared for the new placement
            self._note_dead_owner(info.get("address"))
            info.update(state="RESTARTING", address=None, node_id=None)
            self._publish("actors", {"actor_id": aid, "state": "RESTARTING"})
            asyncio.ensure_future(self._schedule_actor(aid, delay=0.1))
        else:
            await self._fail_actor(aid, reason)

    async def _fail_actor(self, aid: str, reason: str):
        info = self.actors.get(aid)
        if info is None:
            return
        self._actor_dead(aid, info)
        self._note_dead_owner(info.get("address"))
        info.update(state="DEAD", death_cause=reason)
        self._persist_soon()
        self._publish("actors", {"actor_id": aid, "state": "DEAD", "reason": reason})

    async def handle_report_actor_death(self, actor_id: str, reason: str,
                                        expected: bool = False):
        if expected:
            await self._fail_actor(actor_id, reason)
        else:
            await self._on_actor_failure(actor_id, reason)
        return True

    async def handle_list_named_actors(self, namespace: str = "default",
                                       all_namespaces: bool = False):
        """Live named actors (reference: ``GcsActorManager::ListNamedActors``
        behind ``ray.util.list_named_actors``)."""
        out = []
        for (ns, name), aid in self.named_actors.items():
            info = self.actors.get(aid)
            if info is None or info.get("state") == "DEAD":
                continue
            if all_namespaces or ns == namespace:
                out.append({"namespace": ns, "name": name})
        return out

    async def handle_get_actor_info(self, actor_id: Optional[str] = None,
                                    name: Optional[str] = None,
                                    namespace: str = "default"):
        if actor_id is None:
            actor_id = self.named_actors.get((namespace, name))
            if actor_id is None:
                return None
        info = self.actors.get(actor_id)
        if info is None:
            return None
        return {k: v for k, v in info.items() if k != "spec"}

    async def handle_wait_actor_alive(self, actor_id: str, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = self.actors.get(actor_id)
            if info is None:
                return None
            if info["state"] == "ALIVE":
                return {k: v for k, v in info.items() if k != "spec"}
            if info["state"] == "DEAD":
                return {k: v for k, v in info.items() if k != "spec"}
            await asyncio.sleep(0.02)
        return {"state": "TIMEOUT", "actor_id": actor_id}

    async def handle_kill_actor(self, actor_id: str, no_restart: bool = True):
        info = self.actors.get(actor_id)
        if info is None:
            return False
        if no_restart:
            info["restarts_left"] = 0
        addr = info.get("address")
        nid = info.get("node_id")
        if addr and nid and nid in self.nodes:
            agent = self.agent_clients.get(self.nodes[nid].address)
            try:
                await agent.call_retry("kill_worker",
                                       worker_id=info.get("worker_id"),
                                       reason="ray.kill")
            except Exception:
                pass
        if no_restart:
            await self._fail_actor(actor_id, "killed via ray.kill")
        else:
            # Restartable kill: treat like a crash so max_restarts applies
            # (reference: GcsActorManager::DestroyActor vs restart path).
            await self._on_actor_failure(actor_id, "killed via ray.kill(no_restart=False)")
        return True

    async def handle_list_actors(self):
        return [{k: v for k, v in info.items() if k != "spec"}
                for info in self.actors.values()]

    # ---------------------------------------------------------- placement groups

    async def handle_create_placement_group(self, pg_id: str,
                                            bundles: List[Dict[str, float]],
                                            strategy: str, name: str = "",
                                            lifetime: Optional[str] = None):
        self.pgs[pg_id] = {"pg_id": pg_id, "bundles": bundles, "strategy": strategy,
                           "state": "PENDING", "name": name, "placement": None,
                           "lifetime": lifetime, "created_at": time.time()}
        self._pg_events[pg_id] = asyncio.Event()
        self._persist_soon()
        asyncio.ensure_future(self._schedule_pg(pg_id))
        # common case on an uncontended cluster: the placement settles
        # within one agent round trip — piggyback the result on the create
        # reply so the client's ready() needs no second RPC.  Only wait
        # when a placement is packable RIGHT NOW; a pending-forever PG
        # must not add latency to batch creates (the long-poll
        # wait_placement_group remains the general path).
        if pack_bundles(self.nodes, bundles, strategy) is not None:
            ev = self._pg_events.get(pg_id)
            try:
                await asyncio.wait_for(ev.wait(), 0.25)
            except asyncio.TimeoutError:
                pass
        info = self.pgs.get(pg_id)
        return {"pg_id": pg_id,
                "info": info if info and info["state"] != "PENDING" else None}

    def _pg_settled(self, pg_id: str):
        ev = self._pg_events.get(pg_id)
        if ev is not None:
            ev.set()

    async def _schedule_pg(self, pg_id: str):
        info = self.pgs.get(pg_id)
        if info is None:
            return
        last_reason = None
        for attempt in range(200):
            explain: Dict[str, object] = {}
            placement = pack_bundles(self.nodes, info["bundles"],
                                     info["strategy"], explain=explain)
            if placement is None:
                reason = sched_explain.reason_for_no_node(explain)
                if info.get("pending_reason") != reason:
                    info["pending_reason"] = reason
                    info["reason_since"] = time.time()
                if reason != last_reason or attempt % 25 == 0:
                    last_reason = reason
                    self._record_decision({
                        "kind": "pg", "id": pg_id,
                        "label": info.get("name") or pg_id[:12],
                        "demand": list(info["bundles"]),
                        "strategy": info["strategy"],
                        "outcome": "no_placement", "reason": reason,
                        "candidates": explain.get("candidates"),
                        **sched_explain.bound_rejected(
                            explain.get("rejected")),
                        "attempt": attempt})
            if placement is not None:
                # 2-phase prepare/commit (reference PrepareBundleResources/
                # CommitBundleResources), batched to ONE RPC per node per
                # phase; a placement that lands entirely on one node takes
                # the fused prepare_commit path — no cross-node atomicity
                # to coordinate, so one round trip creates the whole PG.
                by_node: Dict[str, Dict[int, dict]] = {}
                for i, nid in enumerate(placement):
                    by_node.setdefault(nid, {})[i] = info["bundles"][i]

                async def _phase(method: str, nid: str, payload) -> bool:
                    agent = self.agent_clients.get(self.nodes[nid].address)
                    try:
                        # retried prepares/commits carry an idempotency
                        # token: a lost reply must not double-reserve
                        return bool(await agent.call_retry(
                            method, pg_id=pg_id, **payload))
                    except Exception:
                        return False

                if len(by_node) == 1:
                    nid, bundles = next(iter(by_node.items()))
                    ok = await _phase("prepare_commit_bundles", nid,
                                      {"bundles": bundles})
                    results = {nid: ok}
                else:
                    results = dict(zip(by_node, await asyncio.gather(
                        *[_phase("prepare_bundles", nid, {"bundles": b})
                          for nid, b in by_node.items()])))
                    if all(results.values()):
                        # a failed COMMIT must also fail the attempt — a
                        # PG published CREATED with an uncommitted bundle
                        # breaks every lease against it
                        commits = await asyncio.gather(
                            *[_phase("commit_bundles", nid,
                                     {"indices": list(b)})
                              for nid, b in by_node.items()])
                        for nid, ok in zip(by_node, commits):
                            results[nid] = results[nid] and ok
                if all(results.values()):
                    info.pop("pending_reason", None)
                    info.pop("reason_since", None)
                    if last_reason is not None:
                        self._record_decision({
                            "kind": "pg", "id": pg_id,
                            "label": info.get("name") or pg_id[:12],
                            "outcome": "placed",
                            "nodes": list(dict.fromkeys(placement)),
                            "attempt": attempt})
                    info.update(state="CREATED",
                                placement=[(nid, self.nodes[nid].address)
                                           for nid in placement])
                    self._persist_soon()
                    self._pg_settled(pg_id)
                    self._publish("pgs", {"pg_id": pg_id, "state": "CREATED"})
                    return
                # Roll back on EVERY node of the attempt, including ones
                # whose prepare/commit RPC failed — a lost reply may have
                # applied server-side, and return_bundles is idempotent
                # (pops whatever exists), so over-returning is safe while
                # under-returning leaks the bundle until agent restart.
                await asyncio.gather(
                    *[_phase("return_bundles", nid, {"indices": list(b)})
                      for nid, b in by_node.items()])
            if self.pgs.get(pg_id) is None:
                return
            # quick first retries (a bundle freed a moment ago — e.g. an
            # async PG removal still returning resources), then back off
            await asyncio.sleep(min(0.02 * (2 ** min(attempt, 4)), 0.25))
        info["state"] = "INFEASIBLE"
        self._pg_settled(pg_id)

    async def handle_get_placement_group(self, pg_id: str):
        return self.pgs.get(pg_id)

    async def handle_wait_placement_group(self, pg_id: str, timeout: float = 60.0):
        info = self.pgs.get(pg_id)
        if info is None:
            return None
        if info["state"] in ("CREATED", "INFEASIBLE"):
            return info
        ev = self._pg_events.get(pg_id)
        if ev is not None:
            try:
                await asyncio.wait_for(ev.wait(), timeout)
            except asyncio.TimeoutError:
                pass
        return self.pgs.get(pg_id)

    async def handle_remove_placement_group(self, pg_id: str):
        info = self.pgs.pop(pg_id, None)
        self._pg_settled(pg_id)
        self._pg_events.pop(pg_id, None)
        if info is None:
            return False
        self._persist_soon()
        if info.get("placement"):
            # resource return is OFF the reply path (reference: removal is
            # async server-side); agents see the return frames before any
            # later prepare from this same GCS connection, and _schedule_pg
            # quick-retries cover scheduling races.
            by_addr: Dict[str, list] = {}
            for i, (nid, addr) in enumerate(info["placement"]):
                if nid in self.nodes:
                    by_addr.setdefault(addr, []).append(i)

            async def _return(addr: str, indices: list):
                try:
                    await self.agent_clients.get(addr).call_retry(
                        "return_bundles", pg_id=pg_id, indices=indices)
                except Exception:
                    pass

            if not hasattr(self, "_bg_tasks"):
                self._bg_tasks = set()
            for addr, indices in by_addr.items():
                # strong ref until done — the loop holds only weak refs,
                # and a GC'd task would leak the bundle's resources forever
                task = asyncio.ensure_future(_return(addr, indices))
                self._bg_tasks.add(task)
                task.add_done_callback(self._bg_tasks.discard)
        self._publish("pgs", {"pg_id": pg_id, "state": "REMOVED"})
        return True

    async def handle_list_placement_groups(self):
        return list(self.pgs.values())

    # ----------------------------------------------------------------- jobs

    async def handle_register_job(self, metadata: dict | None = None):
        self._job_counter += 1
        jid = JobID(self._job_counter.to_bytes(4, "big"))
        self.jobs[jid.hex()] = {"job_id": jid.hex(), "state": "RUNNING",
                                "start_time": time.time(),
                                "metadata": metadata or {}}
        self._persist()
        return jid.hex()

    async def handle_finish_job(self, job_id: str):
        j = self.jobs.get(job_id)
        if j:
            j.update(state="FINISHED", end_time=time.time())
            self._persist()
        # Job-scoped actor GC: non-detached actors die with their job
        # (reference: GcsActorManager::OnJobFinished); detached ones survive.
        # The by-job index holds only LIVE actors, so a job finish is
        # O(its own survivors) regardless of table size.
        for aid in self._live_actors_by_job.get(job_id):
            info = self.actors.get(aid)
            if (info is not None and info.get("lifetime") != "detached"
                    and info["state"] not in ("DEAD",)):
                await self.handle_kill_actor(aid, no_restart=True)
        return True

    async def handle_list_jobs(self):
        return list(self.jobs.values())

    # ------------------------------------------------------------ task events

    async def handle_add_task_events(self, events: List[dict],
                                     dropped: int = 0,
                                     counters: dict | None = None):
        self.task_events.extend(events)
        if dropped:
            # owners shed events past their bounded buffer; keep the gap
            # visible (state API completeness caveat) instead of silent
            self.task_events_dropped += dropped
        if counters:
            # submission-plane counter snapshot piggybacking the flush
            # (cumulative per owner — latest wins; sched_stats rolls up)
            self.submit_plane_counters[counters.get("owner", "?")] = counters
        return True

    async def handle_list_task_events(self, limit: int = 1000,
                                      filters: dict | None = None):
        out = []
        for ev in reversed(self.task_events):
            if filters and any(ev.get(k) != v for k, v in filters.items()):
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        if self._shard_clients:
            # shard-aware writers append to their own shard's ring; the
            # state API sees ONE merged, newest-first stream
            for slice_ in await self._shard_call_all(
                    "list_task_events", limit=limit, filters=filters):
                out.extend(slice_)
            out.sort(key=lambda e: e.get("ts", 0.0), reverse=True)
            del out[limit:]
        return out

    # ------------------------------------------------------- scheduler explain

    def _on_handler_busy(self, method: str, busy_s: float):
        self._handler_busy[method] = \
            self._handler_busy.get(method, 0.0) + busy_s
        self._handler_calls[method] = self._handler_calls.get(method, 0) + 1
        hist = sched_explain.gcs_handler_hist()
        if hist is not None:
            key = self._gcs_hist_keys.get(method)
            if key is None:
                # shard="router" marks this process's slice of the (now
                # bounded-by-process-count) shard tag; shard processes
                # observe shard="<index>" (gcs_shard._on_handler_busy)
                key = self._gcs_hist_keys[method] = (
                    ("method", method), ("shard", "router"))
            hist.observe_key(key, busy_s)

    def _prune_decisions(self):
        max_age = get_config().sched_decision_max_age_s
        if max_age <= 0:
            return
        cutoff = time.time() - max_age
        d = self.sched_decisions
        while d and d[0].get("ts", 0.0) < cutoff:
            d.popleft()

    def _record_decision(self, record: dict):
        record.setdefault("ts", time.time())
        self._prune_decisions()
        self.sched_decisions.append(record)

    async def handle_add_sched_decisions(self, records: List[dict]):
        """Owner-side decision records (lease-acquisition outcomes) land in
        the same ring as the GCS's own actor/PG placement decisions, so
        ``explain`` sees one trail regardless of who decided."""
        self._prune_decisions()
        self.sched_decisions.extend(records)
        return True

    async def handle_get_sched_decisions(self, limit: int = 200,
                                         id: Optional[str] = None,
                                         kind: Optional[str] = None):
        self._prune_decisions()
        out: List[dict] = []
        for rec in reversed(self.sched_decisions):
            if kind is not None and rec.get("kind") != kind:
                continue
            if id is not None and not self._decision_mentions(rec, id):
                continue
            out.append(rec)
            if len(out) >= limit:
                break
        if self._shard_clients:
            for slice_ in await self._shard_call_all(
                    "get_sched_decisions", limit=limit, id=id, kind=kind):
                out.extend(slice_)
            out.sort(key=lambda r: r.get("ts", 0.0), reverse=True)
            del out[limit:]
        return out

    @staticmethod
    def _decision_mentions(rec: dict, id: str) -> bool:
        if rec.get("id") == id:
            return True
        ids = rec.get("task_ids")
        return bool(ids) and id in ids

    async def handle_explain(self, id: str):
        """The full decision trail for one task / actor / placement group:
        its typed pending-reason transitions (task events), the scheduling
        decision records that mention it, and its current table state —
        the payload behind ``state.explain`` / ``raytpu explain``."""
        self._prune_decisions()
        out: Dict[str, object] = {"id": id, "kind": None}
        # task events: reason transitions + lifecycle, oldest first
        events = [ev for ev in self.task_events
                  if ev.get("task_id") == id or ev.get("actor_id") == id]
        if self._shard_clients:
            for slice_ in await self._shard_call_all("find_task_events",
                                                     id=id):
                events.extend(slice_)
        events.sort(key=lambda e: e.get("ts", 0.0))
        if events:
            out["kind"] = "task"
            out["events"] = events
            latest = max((e for e in events
                          if e.get("state") not in ("STAGES", "SPAN")),
                         key=lambda e: e.get("ts", 0.0), default=None)
            if latest is not None:
                out["state"] = latest.get("state")
                out["name"] = latest.get("name")
                if latest.get("state") == "PENDING":
                    out["pending_reason"] = latest.get("reason")
        info = self.actors.get(id)
        if info is not None:
            out["kind"] = "actor"
            out["actor"] = {k: v for k, v in info.items() if k != "spec"}
            out["state"] = info.get("state")
            if info.get("state") not in ("ALIVE",):
                out["pending_reason"] = info.get("pending_reason")
        pg = self.pgs.get(id)
        if pg is not None:
            out["kind"] = "pg"
            out["pg"] = pg
            out["state"] = pg.get("state")
            if pg.get("state") == "PENDING":
                out["pending_reason"] = pg.get("pending_reason")
        label = out.get("name")
        decisions = [rec for rec in self.sched_decisions
                     if self._decision_mentions(rec, id)
                     or (label is not None and rec.get("label") == label)]
        if self._shard_clients:
            for slice_ in await self._shard_call_all(
                    "get_sched_decisions", id=id, limit=100):
                decisions.extend(slice_)
        decisions.sort(key=lambda r: r.get("ts", 0.0))
        out["decisions"] = decisions[-100:]
        return out

    # --------------------------------------------- object flight recorder

    def _prune_object_events(self):
        max_age = get_config().object_event_max_age_s
        if max_age <= 0:
            return
        cutoff = time.time() - max_age
        d = self.object_events
        while d and d[0].get("ts", 0.0) < cutoff:
            d.popleft()

    async def handle_add_object_events(self, events: List[dict],
                                       dropped: int = 0):
        """Batched object lifecycle transitions from node agents and
        owners land in one ring, so ``explain_object`` sees a single
        trail regardless of which process observed the transition."""
        self._prune_object_events()
        self.object_events.extend(events)
        self.object_events_dropped += dropped
        return True

    async def handle_get_object_events(self, limit: int = 200,
                                       id: Optional[str] = None,
                                       event: Optional[str] = None):
        self._prune_object_events()
        out: List[dict] = []
        for rec in reversed(self.object_events):
            if id is not None and rec.get("object_id") != id:
                continue
            if event is not None and rec.get("event") != event:
                continue
            out.append(rec)
            if len(out) >= limit:
                break
        if self._shard_clients:
            for slice_ in await self._shard_call_all(
                    "get_object_events", limit=limit, id=id, event=event):
                out.extend(slice_)
            out.sort(key=lambda r: r.get("ts", 0.0), reverse=True)
            del out[limit:]
        return out

    async def handle_explain_object(self, id: str):
        """The lifecycle trail of ONE object: its transition events
        (oldest first) with owner/location/tier history, its latest
        state, and rollups (copies seen per node, spill tiers touched) —
        the payload behind ``state.explain_object`` / ``raytpu explain
        <object_id>``."""
        self._prune_object_events()
        events = [ev for ev in self.object_events
                  if ev.get("object_id") == id]
        if self._shard_clients:
            for slice_ in await self._shard_call_all(
                    "get_object_events", id=id, limit=1000):
                events.extend(slice_)
        events.sort(key=lambda e: e.get("ts", 0.0))
        out: Dict[str, object] = {"id": id, "kind": None, "events": events}
        if self.object_events_dropped:
            # an incomplete trail should say so: agents shed events past
            # their 10k buffer and ship the count with every flush
            out["events_dropped"] = self.object_events_dropped
        if not events:
            return out
        out["kind"] = "object"
        latest = events[-1]
        out["state"] = latest.get("event")
        out["size"] = next((e.get("size") for e in reversed(events)
                            if e.get("size") is not None), None)
        out["owner"] = next((e.get("owner") for e in reversed(events)
                             if e.get("owner")), None)
        out["nodes"] = sorted({e.get("node") for e in events
                               if e.get("node")})
        out["tiers"] = sorted({e.get("tier") for e in events
                               if e.get("tier")})
        return out

    # ------------------------------------------------------- health plane

    def _prune_health_alerts(self):
        max_age = get_config().health_alert_max_age_s
        if max_age <= 0:
            return
        cutoff = time.time() - max_age
        d = self.health_alerts
        while d and d[0].get("ts", 0.0) < cutoff:
            d.popleft()

    async def handle_add_health_alerts(self, records: List[dict],
                                       active: Optional[List[dict]] = None,
                                       source: str = "head"):
        """Alert transitions from an external detector (the dashboard
        head's scrape-loop rule subset) land in the same ring as the
        GCS's own; ``active`` is that detector's full current active
        set (latest push wins — handle_health merges it while fresh)."""
        self._prune_health_alerts()
        self.health_alerts.extend(records)
        if active is not None:
            self._health_active_ext[source] = {"ts": time.time(),
                                               "active": list(active)}
        return True

    async def handle_get_health_alerts(self, limit: int = 100,
                                       rule: Optional[str] = None,
                                       kind: Optional[str] = None):
        """Newest-first tail of the alert transition ring."""
        self._prune_health_alerts()
        out: List[dict] = []
        for rec in reversed(self.health_alerts):
            if rule is not None and rec.get("rule") != rule:
                continue
            if kind is not None and rec.get("kind") != kind:
                continue
            out.append(rec)
            if len(out) >= limit:
                break
        return out

    async def handle_health(self, limit: int = 50):
        """The ``state.health()`` / ``GET /api/health`` payload: the
        deduplicated active-alert set (GCS-side rules merged with the
        head detector's freshest push) plus the recent transition
        trail."""
        from ray_tpu.util import health as health_plane
        self._prune_health_alerts()
        active: List[dict] = []
        det = self._health_detector
        if det is not None:
            active.extend(det.active())
        horizon = time.time() - max(
            60.0, 4 * get_config().metrics_scrape_period_s)
        for ent in self._health_active_ext.values():
            if ent.get("ts", 0.0) >= horizon:
                active.extend(ent.get("active") or [])
        active.sort(key=lambda a: (a.get("severity") != "critical",
                                   a.get("rule", ""), a.get("scope", "")))
        return {
            "enabled": health_plane.enabled(),
            "active": active,
            "recent": list(self.health_alerts)[-limit:][::-1],
            "ring_len": len(self.health_alerts),
            "rules": sorted(health_plane.HealthRule.ALL),
        }

    async def handle_sched_stats(self):
        """Control-plane saturation rollup: per-handler cumulative busy
        seconds + call counts, the GCS loop's busy fraction, and ring
        occupancy — what ``raytpu status`` / ``/api/sched`` /
        bench_scale.py read to name the bottleneck."""
        mon = getattr(self, "_loop_monitor", None)
        busy = {m: round(s, 6) for m, s in self._handler_busy.items()}
        top = sorted(busy.items(), key=lambda kv: kv[1], reverse=True)
        out = {
            "handler_busy_s": busy,
            "handler_calls": dict(self._handler_calls),
            "top_handlers": top[:10],
            "loop_busy_fraction": getattr(mon, "busy_fraction", None),
            "loop_stalls": getattr(mon, "stall_count", None),
            "decision_ring_len": len(self.sched_decisions),
            "task_events_dropped": self.task_events_dropped,
            "object_events_dropped": self.object_events_dropped,
            "object_event_ring_len": len(self.object_events),
            "sched_metrics_enabled": sched_explain.enabled(),
            "submit_plane": dict(self.submit_plane_counters),
        }
        if self._shard_clients:
            # per-shard rollup: there is no longer ONE GCS loop — status
            # surfaces (raytpu status / top, bench_scale) read each shard
            # process's busy fraction + handler attribution from here
            shards = {}
            for st in await self._shard_call_all("shard_stats"):
                shards[str(st.get("shard"))] = st
            out["shards"] = shards
            out["shard_busy_fractions"] = {
                f"gcs_shard:{k}": v.get("loop_busy_fraction")
                for k, v in shards.items()}
            out["task_events_dropped"] += sum(
                v.get("task_events_dropped") or 0 for v in shards.values())
            out["object_events_dropped"] += sum(
                v.get("object_events_dropped") or 0 for v in shards.values())
            # shard-aware owners flush their task events (and the counter
            # snapshot riding them) straight to a shard — merge the maps
            # so sched_stats shows every owner either way
            for v in shards.values():
                for owner, c in (v.get("submit_plane") or {}).items():
                    out["submit_plane"][owner] = c
        return out

    # ------------------------------------------------------------- debug/info

    async def handle_cluster_info(self):
        return {"started_at": self._started_at,
                "num_nodes": sum(1 for n in self.nodes.values() if n.alive),
                "num_actors": len(self.actors),
                "num_pgs": len(self.pgs),
                "num_jobs": len(self.jobs)}

    async def handle_ping(self):
        return "pong"
