"""GCS shard process — one horizontal slice of the control plane.

Promotes ``core/sharded_table.py``'s in-process hash-partition lines to
PROCESS boundaries (ROADMAP item 5; the Ray paper's sharded-GCS
scalability claim): each shard is a subprocess with its own event loop,
RPC server, snapshot file, and bounded event rings, serving the hot
key-partitionable traffic —

* **namespaced KV** (function registry, workflow step commits) for the
  namespaces that hash to it (``gcs_router.shard_index``),
* **fan-in rings**: task events, object lifecycle events, scheduler
  decision records appended by the owners/agents whose identity hashes
  to it (reads merge across all shards at the router).

Globally-ordered concerns (node table, jobs, actor registration, PG 2PC,
pubsub seq space) stay on the router (``core/gcs.py``) — see
ARCHITECTURE.md "Horizontal control plane" for the split and why.

Per-shard observability: the shard installs its own loop monitor as
``process="gcs_shard:<i>"`` and attributes handler busy seconds into
``raytpu_gcs_handler_seconds{method,shard="<i>"}``; ``shard_stats``
returns the rollup the router aggregates into ``sched_stats``.
"""

from __future__ import annotations

import asyncio
import os
import pickle
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import sched_explain
from .config import get_config
from .rpc import RpcServer
from .sharded_table import SecondaryIndex, ShardedTable


class GcsShardServer:
    """The in-process server object one shard subprocess hosts (tests may
    also run it in-process; nothing here assumes a private process beyond
    the loop monitor's process tag)."""

    def __init__(self, index: int, num_shards: int,
                 host: str = "127.0.0.1", port: int = 0,
                 persistence_path: Optional[str] = None):
        self.index = index
        self.num_shards = num_shards
        self.server = RpcServer(self, host, port)
        cfg = get_config()
        table_shards = max(1, cfg.gcs_table_shards)
        self.kv: ShardedTable = ShardedTable(table_shards)
        self._kv_ns_index = SecondaryIndex()
        self.task_events: deque = deque(maxlen=cfg.task_events_max_buffer)
        self.task_events_dropped = 0
        #: latest submission-plane counter snapshot per owner
        self.submit_plane_counters: Dict[str, dict] = {}
        self.sched_decisions: deque = deque(
            maxlen=max(64, cfg.sched_decision_ring_len))
        self.object_events: deque = deque(
            maxlen=max(64, cfg.object_event_ring_len))
        self.object_events_dropped = 0
        self.persistence_path = persistence_path
        self._handler_busy: Dict[str, float] = {}
        self._handler_calls: Dict[str, int] = {}
        self._hist_keys: Dict[str, tuple] = {}
        self._started_at = time.time()

    # ------------------------------------------------------------------ boot

    async def start(self):
        self._maybe_restore()
        if sched_explain.enabled():
            self.server.busy_cb = self._on_handler_busy
        await self.server.start()
        from ray_tpu.util.loop_monitor import install as _install_loop_mon
        self._loop_monitor = _install_loop_mon(
            asyncio.get_event_loop(), f"gcs_shard:{self.index}")
        return self

    @property
    def address(self) -> str:
        return self.server.address

    async def stop(self):
        if getattr(self, "_loop_monitor", None):
            self._loop_monitor.stop()
        await self.server.stop()

    # ----------------------------------------------------------- persistence

    def _maybe_restore(self):
        p = self.persistence_path
        if p and os.path.exists(p):
            with open(p, "rb") as f:
                snap = pickle.load(f)
            # entry-by-entry like the router: gcs_table_shards may change
            # between incarnations (the PROCESS-shard count may not — the
            # snapshot records it so a mismatch fails loudly instead of
            # silently serving misrouted keys)
            snapped = snap.get("num_shards")
            if snapped is not None and snapped != self.num_shards:
                raise RuntimeError(
                    f"shard snapshot {p} was written for "
                    f"gcs_shard_processes={snapped}, booting with "
                    f"{self.num_shards} — resharding persisted state is "
                    "not supported")
            for k, v in snap.get("kv", {}).items():
                self.kv[k] = v
                self._kv_ns_index.add(k[0], k[1])

    def _persist(self):
        p = self.persistence_path
        if not p:
            return
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"kv": self.kv.to_dict(),
                         "index": self.index,
                         "num_shards": self.num_shards}, f)
        os.replace(tmp, p)

    # ------------------------------------------------------------------- KV
    #
    # Same handler contracts as the router's pre-shard KV (synchronous
    # persistence on mutation: a workflow step's commit must be on disk
    # before its kv_put acks).

    async def handle_kv_put(self, ns: str, key: str, value: bytes,
                            overwrite: bool = True):
        k = (ns, key)
        if not overwrite and k in self.kv:
            return False
        self.kv[k] = value
        self._kv_ns_index.add(ns, key)
        self._persist()
        return True

    async def handle_kv_get(self, ns: str, key: str):
        return self.kv.get((ns, key))

    async def handle_kv_multi_get(self, ns: str, keys: List[str]):
        return {k: self.kv[(ns, k)] for k in keys if (ns, k) in self.kv}

    async def handle_kv_del(self, ns: str, key: str):
        existed = self.kv.pop((ns, key), None) is not None
        if existed:
            self._kv_ns_index.discard(ns, key)
            self._persist()
        return existed

    async def handle_kv_keys(self, ns: str, prefix: str = ""):
        return [k for k in self._kv_ns_index.get(ns) if k.startswith(prefix)]

    async def handle_kv_exists(self, ns: str, key: str):
        return (ns, key) in self.kv

    # ------------------------------------------------------------ event rings
    #
    # Identical write contracts to the router's rings; reads return this
    # shard's slice — the router merges slices for the state API.

    async def handle_add_task_events(self, events: List[dict],
                                     dropped: int = 0,
                                     counters: dict | None = None):
        self.task_events.extend(events)
        if dropped:
            self.task_events_dropped += dropped
        if counters:
            # latest submission-plane snapshot per owner (shard-local;
            # the router merges shard maps into its sched_stats rollup)
            self.submit_plane_counters[counters.get("owner", "?")] = counters
        return True

    async def handle_list_task_events(self, limit: int = 1000,
                                      filters: dict | None = None):
        out = []
        for ev in reversed(self.task_events):
            if filters and any(ev.get(k) != v for k, v in filters.items()):
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        return out

    async def handle_find_task_events(self, id: str):
        """Events mentioning one task/actor id (the router's explain
        fan-out primitive)."""
        return [ev for ev in self.task_events
                if ev.get("task_id") == id or ev.get("actor_id") == id]

    def _prune_object_events(self):
        max_age = get_config().object_event_max_age_s
        if max_age <= 0:
            return
        cutoff = time.time() - max_age
        d = self.object_events
        while d and d[0].get("ts", 0.0) < cutoff:
            d.popleft()

    async def handle_add_object_events(self, events: List[dict],
                                       dropped: int = 0):
        self._prune_object_events()
        self.object_events.extend(events)
        self.object_events_dropped += dropped
        return True

    async def handle_get_object_events(self, limit: int = 200,
                                       id: Optional[str] = None,
                                       event: Optional[str] = None):
        self._prune_object_events()
        out: List[dict] = []
        for rec in reversed(self.object_events):
            if id is not None and rec.get("object_id") != id:
                continue
            if event is not None and rec.get("event") != event:
                continue
            out.append(rec)
            if len(out) >= limit:
                break
        return out

    def _prune_decisions(self):
        max_age = get_config().sched_decision_max_age_s
        if max_age <= 0:
            return
        cutoff = time.time() - max_age
        d = self.sched_decisions
        while d and d[0].get("ts", 0.0) < cutoff:
            d.popleft()

    async def handle_add_sched_decisions(self, records: List[dict]):
        self._prune_decisions()
        self.sched_decisions.extend(records)
        return True

    async def handle_get_sched_decisions(self, limit: int = 200,
                                         id: Optional[str] = None,
                                         kind: Optional[str] = None):
        self._prune_decisions()
        out: List[dict] = []
        for rec in reversed(self.sched_decisions):
            if kind is not None and rec.get("kind") != kind:
                continue
            if id is not None and not (
                    rec.get("id") == id
                    or (rec.get("task_ids") and id in rec["task_ids"])):
                continue
            out.append(rec)
            if len(out) >= limit:
                break
        return out

    # ---------------------------------------------------------------- stats

    def _on_handler_busy(self, method: str, busy_s: float):
        self._handler_busy[method] = \
            self._handler_busy.get(method, 0.0) + busy_s
        self._handler_calls[method] = self._handler_calls.get(method, 0) + 1
        hist = sched_explain.gcs_handler_hist()
        if hist is not None:
            key = self._hist_keys.get(method)
            if key is None:
                key = self._hist_keys[method] = (
                    ("method", method), ("shard", str(self.index)))
            hist.observe_key(key, busy_s)

    async def handle_shard_stats(self):
        mon = getattr(self, "_loop_monitor", None)
        busy = {m: round(s, 6) for m, s in self._handler_busy.items()}
        return {
            "shard": self.index,
            "handler_busy_s": busy,
            "handler_calls": dict(self._handler_calls),
            "loop_busy_fraction": getattr(mon, "busy_fraction", None),
            "loop_stalls": getattr(mon, "stall_count", None),
            "kv_entries": len(self.kv),
            "task_event_ring_len": len(self.task_events),
            "task_events_dropped": self.task_events_dropped,
            "object_event_ring_len": len(self.object_events),
            "object_events_dropped": self.object_events_dropped,
            "decision_ring_len": len(self.sched_decisions),
            "submit_plane": dict(self.submit_plane_counters),
            "pid": os.getpid(),
        }

    async def handle_ping(self):
        return "pong"


# --------------------------------------------------------------- spawning

def spawn_shard_processes(num: int, persistence_path: Optional[str],
                          session_dir: Optional[str] = None,
                          only_index: Optional[int] = None
                          ) -> List[Tuple[object, str]]:
    """Spawn shard subprocesses; -> [(Popen, address), ...].

    Spawns all ``num`` shard indices, or just ``only_index`` (the
    supervisor's respawn path — the replacement keeps its index, so its
    snapshot file and key ownership are unchanged).  Each shard persists
    to ``{persistence_path}.shard{i}`` (nothing when persistence is off).
    The shards inherit this process's config via RAYTPU_CONFIG_JSON so
    chaos specs / table-shard counts agree."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["RAYTPU_CONFIG_JSON"] = get_config().to_json()
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    indices = range(num) if only_index is None else [only_index]
    for i in indices:
        cmd = [sys.executable, "-m", "ray_tpu.core.gcs_shard",
               "--index", str(i), "--num-shards", str(num)]
        if persistence_path:
            cmd += ["--persist", f"{persistence_path}.shard{i}"]
        stderr = subprocess.DEVNULL
        if session_dir:
            logs = os.path.join(session_dir, "logs")
            os.makedirs(logs, exist_ok=True)
            stderr = open(os.path.join(logs, f"gcs-shard-{i}.log"),
                          "ab", buffering=0)
        procs.append(subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=stderr, env=env))
    out = []
    import json as _json
    for i, proc in zip(indices, procs):
        line = proc.stdout.readline().decode()
        if not line.strip():
            # the child died before its handshake (import error, port
            # bind failure): fail LOUDLY with the place to look, and
            # reap everything already spawned instead of leaking it
            for p in procs:
                try:
                    p.kill()
                except Exception:
                    pass
            where = (os.path.join(session_dir, "logs", f"gcs-shard-{i}.log")
                     if session_dir else "(stderr discarded; pass a "
                     "session_dir for shard logs)")
            raise RuntimeError(
                f"GCS shard {i} exited before its handshake "
                f"(rc={proc.poll()}); see {where}")
        info = _json.loads(line)
        out.append((proc, info["address"]))
    return out


def main():
    import argparse
    import json
    import signal

    p = argparse.ArgumentParser()
    p.add_argument("--index", type=int, required=True)
    p.add_argument("--num-shards", type=int, required=True)
    p.add_argument("--persist", type=str, default="")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    args = p.parse_args()

    from .config import Config, set_config
    cfg_json = os.environ.get("RAYTPU_CONFIG_JSON")
    if cfg_json:
        set_config(Config.from_json(cfg_json))
    from .rpc import run_async

    shard = GcsShardServer(args.index, args.num_shards, host=args.host,
                           port=args.port,
                           persistence_path=args.persist or None)
    run_async(shard.start())
    print(json.dumps({"address": shard.address, "index": args.index,
                      "pid": os.getpid()}), flush=True)

    stop = False
    parent = os.getppid()

    def _sig(*_a):
        nonlocal stop
        stop = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    while not stop:
        time.sleep(0.2)
        # Parent-death watch: a router killed without SIGTERM-ing its
        # fleet (kill -9, OOM) must not leave orphan shards running — a
        # RESTARTED router spawns fresh shards sharing these snapshot
        # paths, and an orphan's late persist could clobber a commit the
        # replacement already acked as durable.
        if os.getppid() != parent:
            break
    run_async(shard.stop(), timeout=5)


if __name__ == "__main__":
    main()
