"""Standalone node agent process entrypoint (reference: ``src/ray/raylet/main.cc:119``).

Used by `Cluster.add_node` to run extra "nodes" on one machine, and by `raytpu start`
to join a real multi-host cluster.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--gcs-address", required=True)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", type=str, default="{}")
    p.add_argument("--labels", type=str, default="{}")
    p.add_argument("--session-dir", type=str, default="/tmp/raytpu")
    p.add_argument("--object-store-memory", type=int, default=0)
    args = p.parse_args()

    from .config import Config, set_config
    cfg_json = os.environ.get("RAYTPU_CONFIG_JSON")
    if cfg_json:
        set_config(Config.from_json(cfg_json))

    from .node_agent import NodeAgent
    from .rpc import get_loop, run_async

    agent = NodeAgent(args.gcs_address,
                      num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                      resources=json.loads(args.resources),
                      labels=json.loads(args.labels),
                      session_dir=args.session_dir,
                      object_store_memory=args.object_store_memory)
    run_async(agent.start())
    # A preempted standalone node's PROCESS must disappear (the "VM" is
    # gone): exit hard from the drain path, no orderly unwind.
    agent._on_preempt_exit = lambda graceful: os._exit(0)
    # Report our address on stdout so the parent can address this node.
    print(json.dumps({"node_id": agent.node_id.hex(),
                      "address": agent.address}), flush=True)

    stop = False

    def _sig(*_a):
        nonlocal stop
        stop = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    import time
    while not stop:
        time.sleep(0.2)
    run_async(agent.stop(), timeout=10)


if __name__ == "__main__":
    main()
