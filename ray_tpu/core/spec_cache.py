"""TaskSpec template cache — the submission hot path's serialization plane.

A process submitting the same function (or actor method) thousands of times
re-pickles the same invariant spec fields — function descriptor, options,
resources, runtime-env — on every call, and the executor re-unpickles them.
This module splits a :class:`~ray_tpu.core.common.TaskSpec` into

* a **template**: every field invariant across calls of one
  ``(function, options)`` pair (or one actor method), pickled ONCE and
  addressed by a 16-byte content hash; and
* a **delta**: the per-call fields (``task_id``, ``args``, ``retry_count``,
  ``seq_no``, ``trace_ctx``) that ride every submission.

The sender keeps a bounded LRU of encoded templates keyed by the spec's
template key and tracks, per RPC connection, which template hashes the peer
has already received — so steady-state submissions wire-encode only the
hash plus the delta.  The receiver interns decoded templates by hash in a
bounded LRU of prototype specs; decoding a warm submission is a generated
field-copy clone plus six volatile stores, no pickling of the invariant
portion at all (TaskSpec is ``__slots__``-based, so clones are slot copies,
not ``__dict__`` copies).

**Packed batch frames** (the native submission plane): a warm push batch
whose specs are all template-cacheable wire-encodes into ONE flat binary
blob (``pack_specs``) instead of a list of per-spec tuples — the RPC
layer's pickle then sees a single bytes object (one memcpy) rather than
N nested tuples.  Each record is a fixed 52-byte header —

    thash(16) | task_id(16) | retry u32 | seq u64 | args_len u32
    | trace_len u32

— followed by the args blob and the (rare, pickled) trace context.  The
packer/scanner pair lives in ``ray_tpu/native/submit_plane.cpp`` (plain C
ABI via ctypes, same toolchain as shm_pool.cpp); a pure-Python
struct-based fallback produces byte-identical frames when the .so is
absent or ``submit_plane_native_enabled`` is off for the C path.  The
per-template wire-invariant header bytes (the 16-byte content hash that
prefixes every record of that template) are precomputed once per
(function, options) pair and cached in the sender LRU entry.

Redefinition is handled by content addressing: a changed function or option
set produces a different template key AND hash, and stale entries age out
of both LRUs (eviction-on-redefine).  A receiver that evicted a template a
sender still believes is delivered raises :class:`SpecCacheMiss`; the
sender forgets its delivered-set for that connection and resends the full
template (the handler raised before executing anything, so the resend is
safe).

Reference analogue: the reference ships functions by content hash through
the GCS function table (``python/ray/_private/function_manager.py``) for
exactly this reason; here the same interning is applied to the whole
invariant spec portion on the direct task-transport path.
"""

from __future__ import annotations

import collections
import hashlib
import pickle
import struct
import threading
import time
from typing import List, Optional, Tuple

from .common import TEMPLATE_FIELDS, TaskSpec, copy_template_into
from .common import VOLATILE_FIELDS  # noqa: F401  (re-export, long-time home)
from .config import get_config
from .ids import TaskID

#: wire tag for a template-cached spec (anything else decodes as-is)
_WIRE_TAG = "tspec"

#: wire tag for a packed batch frame (``("sp1", blob, templates)``)
_PACK_TAG = "sp1"

#: packed-frame layout: 4-byte magic + u32 record count, then per record a
#: fixed header ``thash(16) task_id(16) retry(u32) seq(u64) args_len(u32)
#: trace_len(u32)`` followed by the variable payloads.
_PACK_MAGIC = b"SP01"
_PACK_HDR = struct.Struct("<IQII")     # retry, seq, args_len, trace_len
_REC_FIXED = 32 + _PACK_HDR.size       # 52 bytes

#: args blobs at least this large ride as out-of-band pickle-5 buffers in
#: the wire delta (same threshold as the RPC layer's vectored frames).
#: Packed frames keep the same discipline: a batch containing an args blob
#: this large falls back to per-spec tuples so the big payload stays OOB
#: instead of being copied through the packed frame.
from .rpc import _VEC_MIN_BUF as _OOB_ARGS_MIN


class SpecCacheMiss(Exception):
    """The receiver does not hold the template a hash-only submission
    references (its LRU evicted it, or a reordered first frame).  Raised
    BEFORE any task is dispatched, so the sender may safely resend the
    batch with the full template included."""


def _template_key(spec: TaskSpec) -> tuple:
    """Cheap hashable identity of the spec's invariant portion.  Must cover
    every non-volatile field that can differ between two specs a process
    submits — a collision here would run a task under another template's
    options."""
    return (
        spec.is_actor_task,
        spec.fn_id,
        spec.actor_id.binary() if spec.actor_id is not None else None,
        spec.actor_method,
        spec.name,
        spec.num_returns,
        tuple(sorted(spec.resources.items())) if spec.resources else (),
        repr(spec.scheduling_strategy),
        spec.max_retries,
        spec.retry_exceptions,
        repr(sorted(spec.runtime_env.items())) if spec.runtime_env else None,
        spec.generator_backpressure,
        spec.owner,
        spec.job_id.binary(),
        # constant defaults on task/method specs today, but covered so a
        # future path that sets them cannot collide two templates
        spec.max_restarts, spec.max_task_retries, spec.max_concurrency,
        spec.is_async_actor, spec.actor_name, spec.namespace, spec.lifetime,
    )


def _template_fields(spec: TaskSpec) -> dict:
    return {n: getattr(spec, n) for n in TEMPLATE_FIELDS}


# --------------------------------------------------------------- packing
#
# The pure-Python packer/scanner below and the C pair in
# native/submit_plane.cpp MUST produce byte-identical frames — the
# round-trip test in tests/test_submit_plane_native.py pins this.

def _py_pack(recs: List[tuple]) -> bytearray:
    """recs: [(thash, task_id_bin, retry, seq, args, trace_blob)]."""
    total = 8
    for _h, _t, _r, _s, a, tr in recs:
        total += _REC_FIXED + len(a) + len(tr)
    buf = bytearray(total)
    buf[0:4] = _PACK_MAGIC
    struct.pack_into("<I", buf, 4, len(recs))
    off = 8
    pack_hdr = _PACK_HDR.pack_into
    for h, t, r, s, a, tr in recs:
        buf[off:off + 16] = h
        buf[off + 16:off + 32] = t
        pack_hdr(buf, off + 32, r, s, len(a), len(tr))
        off += _REC_FIXED
        na = len(a)
        buf[off:off + na] = a
        off += na
        if tr:
            buf[off:off + len(tr)] = tr
            off += len(tr)
    return buf


def _native_pack(recs: List[tuple]) -> Optional[bytearray]:
    """Pack via the C extension; None when the .so is unavailable (caller
    uses the byte-identical pure-Python path)."""
    from ..native import load_submit_plane
    lib = load_submit_plane()
    if lib is None:
        return None
    import ctypes
    n = len(recs)
    total = 8
    for _h, _t, _r, _s, a, tr in recs:
        total += _REC_FIXED + len(a) + len(tr)
    buf = bytearray(total)
    hashes = b"".join(r[0] for r in recs)
    tids = b"".join(r[1] for r in recs)
    retries = (ctypes.c_uint32 * n)(*[r[2] for r in recs])
    seqs = (ctypes.c_uint64 * n)(*[r[3] for r in recs])
    args_ptrs = (ctypes.c_char_p * n)(*[r[4] for r in recs])
    args_lens = (ctypes.c_uint32 * n)(*[len(r[4]) for r in recs])
    trace_ptrs = (ctypes.c_char_p * n)(*[r[5] or None for r in recs])
    trace_lens = (ctypes.c_uint32 * n)(*[len(r[5]) for r in recs])
    out = (ctypes.c_char * total).from_buffer(buf)
    wrote = lib.sp_pack(out, total, n, hashes, tids, retries, seqs,
                        args_ptrs, args_lens, trace_ptrs, trace_lens)
    if wrote != total:
        return None
    return buf


def pack_specs(recs: List[tuple]) -> bytearray:
    """One flat frame for a warm batch — C when available and enabled,
    byte-identical pure Python otherwise."""
    if get_config().submit_plane_native_enabled:
        out = _native_pack(recs)
        if out is not None:
            return out
    return _py_pack(recs)


def unpack_specs(blob) -> List[tuple]:
    """-> [(thash, task_id_bin, retry, seq, args_bytes, trace_blob)].
    Scans with the C extension when present (offset/length arrays filled
    natively, Python only slices); falls back to the struct scanner."""
    mv = memoryview(blob)
    if len(mv) < 8 or bytes(mv[0:4]) != _PACK_MAGIC:
        raise SpecCacheMiss("malformed packed spec frame (bad magic)")
    (n,) = struct.unpack_from("<I", mv, 4)
    out: List[tuple] = []
    offs = _native_scan(mv, n)
    if offs is not None:
        for off, retry, seq, alen, tlen in offs:
            h = bytes(mv[off:off + 16])
            tid = bytes(mv[off + 16:off + 32])
            p = off + _REC_FIXED
            args = bytes(mv[p:p + alen])
            trace = bytes(mv[p + alen:p + alen + tlen]) if tlen else b""
            out.append((h, tid, retry, seq, args, trace))
        return out
    off = 8
    end = len(mv)
    for _ in range(n):
        if off + _REC_FIXED > end:
            raise SpecCacheMiss("truncated packed spec frame")
        h = bytes(mv[off:off + 16])
        tid = bytes(mv[off + 16:off + 32])
        retry, seq, alen, tlen = _PACK_HDR.unpack_from(mv, off + 32)
        off += _REC_FIXED
        if off + alen + tlen > end:
            raise SpecCacheMiss("truncated packed spec frame")
        args = bytes(mv[off:off + alen])
        off += alen
        trace = bytes(mv[off:off + tlen]) if tlen else b""
        off += tlen
        out.append((h, tid, retry, seq, args, trace))
    return out


def _native_scan(mv: memoryview, n: int):
    """C record scan -> [(rec_off, retry, seq, args_len, trace_len)], or
    None to use the pure-Python scanner."""
    if not get_config().submit_plane_native_enabled or n == 0:
        return None
    from ..native import load_submit_plane
    lib = load_submit_plane()
    if lib is None:
        return None
    import ctypes
    if mv.readonly:
        src = (ctypes.c_char * len(mv)).from_buffer_copy(mv)
    else:
        src = (ctypes.c_char * len(mv)).from_buffer(mv)
    rec_offs = (ctypes.c_uint64 * n)()
    retries = (ctypes.c_uint32 * n)()
    seqs = (ctypes.c_uint64 * n)()
    args_lens = (ctypes.c_uint32 * n)()
    trace_lens = (ctypes.c_uint32 * n)()
    got = lib.sp_scan(src, len(mv), n, rec_offs, retries, seqs,
                      args_lens, trace_lens)
    if got != n:
        raise SpecCacheMiss("truncated packed spec frame")
    return [(rec_offs[i], retries[i], seqs[i], args_lens[i], trace_lens[i])
            for i in range(n)]


class SpecEncoder:
    """Sender side: one per CoreWorker.  ``encode`` returns either the raw
    TaskSpec (cache disabled / actor-creation specs) or the compact wire
    tuple, including the template blob only when this connection has not
    seen the hash yet.  ``encode_batch`` returns the packed frame for a
    fully warm-packable batch, or None (caller encodes per spec)."""

    def __init__(self):
        # template key -> (hash, blob); LRU by move-to-end on hit.  The
        # hash doubles as the packed record's precomputed wire-invariant
        # header bytes — computed once per (function, options) pair.  The
        # lock covers the OrderedDict relinks: with owner_serialize_threads
        # the encoder runs on pool threads concurrently, and move_to_end/
        # popitem are not atomic under the GIL.
        self._lru: "collections.OrderedDict[tuple, Tuple[bytes, bytes]]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def _template_for(self, spec: TaskSpec) -> Tuple[bytes, bytes]:
        key = _template_key(spec)
        with self._lock:
            hit = self._lru.get(key)
            if hit is not None:
                self._lru.move_to_end(key)
                return hit
        blob = pickle.dumps(_template_fields(spec), protocol=5)
        thash = hashlib.blake2b(blob, digest_size=16).digest()
        with self._lock:
            self._lru[key] = (thash, blob)
            cap = max(get_config().spec_cache_max_entries, 8)
            while len(self._lru) > cap:
                self._lru.popitem(last=False)
        return thash, blob

    @staticmethod
    def _delivered_set(client) -> set:
        """Hashes the peer has received ON THE CURRENT CONNECTION.  Keyed
        by writer identity: a reconnect installs a fresh writer, and the
        receiver interns process-globally, so stale entries only ever cause
        a redundant template resend, never a miss."""
        w = client._writer
        rec = getattr(client, "_raytpu_tmpl_sent", None)
        if rec is None or rec[0] is not w:
            rec = client._raytpu_tmpl_sent = (w, set())
        return rec[1]

    @staticmethod
    def forget_client(client) -> None:
        """Drop the delivered-set after a :class:`SpecCacheMiss` so the
        next encode resends full templates."""
        client._raytpu_tmpl_sent = None

    def encode(self, client, spec: TaskSpec):
        if not get_config().spec_cache_enabled or spec.is_actor_creation:
            return spec
        thash, blob = self._template_for(spec)
        sent = self._delivered_set(client)
        if thash in sent:
            tblob = None
        else:
            tblob = blob
            sent.add(thash)
        args = spec.args
        if isinstance(args, bytes) and len(args) >= _OOB_ARGS_MIN:
            args = pickle.PickleBuffer(args)
        return (_WIRE_TAG, thash, tblob, spec.task_id, args,
                spec.retry_count, spec.seq_no, spec.trace_ctx)

    def encode_batch(self, client, specs: List[TaskSpec]):
        """Packed-frame encode for a warm batch: ``("sp1", blob,
        templates)`` where ``templates`` carries (hash, blob) pairs this
        connection has not seen.  None when any spec is ineligible (cache
        disabled, actor creation, oversized args that must ride OOB, or a
        non-bytes args payload) — the caller falls back to per-spec
        ``encode``, keeping frame order identical either way."""
        cfg = get_config()
        if not (cfg.submit_plane_native_enabled and cfg.spec_cache_enabled):
            return None
        for s in specs:
            if (s.is_actor_creation or not isinstance(s.args, bytes)
                    or len(s.args) >= _OOB_ARGS_MIN):
                return None
        sent = self._delivered_set(client)
        templates: List[Tuple[bytes, bytes]] = []
        recs: List[tuple] = []
        for s in specs:
            thash, blob = self._template_for(s)
            if thash not in sent:
                sent.add(thash)
                templates.append((thash, blob))
            trace = pickle.dumps(s.trace_ctx, protocol=4) \
                if s.trace_ctx is not None else b""
            recs.append((thash, s.task_id.binary(), s.retry_count,
                         s.seq_no, s.args, trace))
        blob = pack_specs(recs)
        wire_blob = pickle.PickleBuffer(bytes(blob)) \
            if len(blob) >= _OOB_ARGS_MIN else bytes(blob)
        return (_PACK_TAG, wire_blob, templates)


class SpecInterner:
    """Receiver side: process-global intern table hash -> prototype spec.
    Decoding clones the prototype (generated slot-field copy) and stores
    the six volatile fields — no pickling of the invariant portion on warm
    submissions."""

    def __init__(self):
        self._lru: "collections.OrderedDict[bytes, TaskSpec]" = \
            collections.OrderedDict()

    def _intern(self, thash: bytes, tblob: bytes) -> TaskSpec:
        proto = TaskSpec.__new__(TaskSpec)
        fields = pickle.loads(tblob)
        for k, v in fields.items():
            setattr(proto, k, v)
        self._lru[thash] = proto
        cap = max(get_config().spec_cache_max_entries, 8)
        while len(self._lru) > cap:
            self._lru.popitem(last=False)
        return proto

    def _proto_for(self, thash: bytes, tblob) -> TaskSpec:
        proto = self._lru.get(thash)
        if proto is None:
            if tblob is None:
                raise SpecCacheMiss(
                    f"unknown spec template {thash.hex()[:16]} "
                    "(receiver cache evicted it?)")
            proto = self._intern(thash, tblob)
        else:
            self._lru.move_to_end(thash)
        return proto

    def _clone(self, proto: TaskSpec, task_id, args, retry_count, seq_no,
               trace_ctx) -> TaskSpec:
        spec = TaskSpec.__new__(TaskSpec)
        copy_template_into(proto, spec)
        spec.task_id = task_id
        spec.args = args if isinstance(args, bytes) else bytes(args)
        spec.retry_count = retry_count
        spec.seq_no = seq_no
        spec.trace_ctx = trace_ctx
        spec.submitted_at = time.time()
        return spec

    def decode(self, wire) -> TaskSpec:
        if isinstance(wire, TaskSpec):
            return wire
        if not (isinstance(wire, tuple) and len(wire) == 8
                and wire[0] == _WIRE_TAG):
            raise TypeError(f"not a task spec wire form: {type(wire)}")
        _tag, thash, tblob, task_id, args, retry_count, seq_no, trace_ctx = \
            wire
        proto = self._proto_for(thash, tblob)
        return self._clone(proto, task_id, args, retry_count, seq_no,
                           trace_ctx)

    def decode_packed(self, wire) -> List[TaskSpec]:
        """Decode a ``("sp1", blob, templates)`` frame.  Templates intern
        first; an unknown record hash then raises :class:`SpecCacheMiss`
        before any spec is acted on (all-or-nothing, same contract as
        ``decode_many``)."""
        _tag, blob, templates = wire
        for thash, tblob in templates:
            if thash not in self._lru:
                self._intern(thash, tblob)
        recs = unpack_specs(blob)
        protos = [self._proto_for(h, None) for
                  (h, _t, _r, _s, _a, _tr) in recs]
        out: List[TaskSpec] = []
        for proto, (_h, tid, retry, seq, args, trace) in zip(protos, recs):
            trace_ctx = pickle.loads(trace) if trace else None
            out.append(self._clone(proto, TaskID(tid), args, retry, seq,
                                   trace_ctx))
        return out


_interner: Optional[SpecInterner] = None


def interner() -> SpecInterner:
    global _interner
    if _interner is None:
        _interner = SpecInterner()
    return _interner


def decode(wire) -> TaskSpec:
    return interner().decode(wire)


def decode_many(wires) -> list:
    """Decode a batch — either a packed ``("sp1", ...)`` frame or a list
    of per-spec wire forms — raising :class:`SpecCacheMiss` before any
    spec is acted on (the all-or-nothing contract the resend path relies
    on)."""
    it = interner()
    if isinstance(wires, tuple) and len(wires) == 3 \
            and wires[0] == _PACK_TAG:
        return it.decode_packed(wires)
    return [it.decode(w) for w in wires]
