"""TaskSpec template cache — the submission hot path's serialization plane.

A process submitting the same function (or actor method) thousands of times
re-pickles the same invariant spec fields — function descriptor, options,
resources, runtime-env — on every call, and the executor re-unpickles them.
This module splits a :class:`~ray_tpu.core.common.TaskSpec` into

* a **template**: every field invariant across calls of one
  ``(function, options)`` pair (or one actor method), pickled ONCE and
  addressed by a 16-byte content hash; and
* a **delta**: the per-call fields (``task_id``, ``args``, ``retry_count``,
  ``seq_no``, ``trace_ctx``) that ride every submission.

The sender keeps a bounded LRU of encoded templates keyed by the spec's
template key and tracks, per RPC connection, which template hashes the peer
has already received — so steady-state submissions wire-encode only the
hash plus the delta.  The receiver interns decoded templates by hash in a
bounded LRU of prototype specs; decoding a warm submission is a ``__dict__``
copy plus five field stores, no pickling of the invariant portion at all.

Redefinition is handled by content addressing: a changed function or option
set produces a different template key AND hash, and stale entries age out
of both LRUs (eviction-on-redefine).  A receiver that evicted a template a
sender still believes is delivered raises :class:`SpecCacheMiss`; the
sender forgets its delivered-set for that connection and resends the full
template (the handler raised before executing anything, so the resend is
safe).

Reference analogue: the reference ships functions by content hash through
the GCS function table (``python/ray/_private/function_manager.py``) for
exactly this reason; here the same interning is applied to the whole
invariant spec portion on the direct task-transport path.
"""

from __future__ import annotations

import collections
import hashlib
import pickle
import threading
import time
from typing import Optional, Tuple

from .common import TaskSpec
from .config import get_config

#: wire tag for a template-cached spec (anything else decodes as-is)
_WIRE_TAG = "tspec"

#: TaskSpec fields that vary per call — everything else is template.
VOLATILE_FIELDS = ("task_id", "args", "retry_count", "seq_no", "trace_ctx",
                   "submitted_at")

#: args blobs at least this large ride as out-of-band pickle-5 buffers in
#: the wire delta (same threshold as the RPC layer's vectored frames).
from .rpc import _VEC_MIN_BUF as _OOB_ARGS_MIN


class SpecCacheMiss(Exception):
    """The receiver does not hold the template a hash-only submission
    references (its LRU evicted it, or a reordered first frame).  Raised
    BEFORE any task is dispatched, so the sender may safely resend the
    batch with the full template included."""


def _template_key(spec: TaskSpec) -> tuple:
    """Cheap hashable identity of the spec's invariant portion.  Must cover
    every non-volatile field that can differ between two specs a process
    submits — a collision here would run a task under another template's
    options."""
    return (
        spec.is_actor_task,
        spec.fn_id,
        spec.actor_id.binary() if spec.actor_id is not None else None,
        spec.actor_method,
        spec.name,
        spec.num_returns,
        tuple(sorted(spec.resources.items())) if spec.resources else (),
        repr(spec.scheduling_strategy),
        spec.max_retries,
        spec.retry_exceptions,
        repr(sorted(spec.runtime_env.items())) if spec.runtime_env else None,
        spec.generator_backpressure,
        spec.owner,
        spec.job_id.binary(),
        # constant defaults on task/method specs today, but covered so a
        # future path that sets them cannot collide two templates
        spec.max_restarts, spec.max_task_retries, spec.max_concurrency,
        spec.is_async_actor, spec.actor_name, spec.namespace, spec.lifetime,
    )


def _template_fields(spec: TaskSpec) -> dict:
    d = dict(spec.__dict__)
    for f in VOLATILE_FIELDS:
        d.pop(f, None)
    return d


class SpecEncoder:
    """Sender side: one per CoreWorker.  ``encode`` returns either the raw
    TaskSpec (cache disabled / actor-creation specs) or the compact wire
    tuple, including the template blob only when this connection has not
    seen the hash yet."""

    def __init__(self):
        # template key -> (hash, blob); LRU by move-to-end on hit.  The
        # lock covers the OrderedDict relinks: with owner_serialize_threads
        # the encoder runs on pool threads concurrently, and move_to_end/
        # popitem are not atomic under the GIL.
        self._lru: "collections.OrderedDict[tuple, Tuple[bytes, bytes]]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def _template_for(self, spec: TaskSpec) -> Tuple[bytes, bytes]:
        key = _template_key(spec)
        with self._lock:
            hit = self._lru.get(key)
            if hit is not None:
                self._lru.move_to_end(key)
                return hit
        blob = pickle.dumps(_template_fields(spec), protocol=5)
        thash = hashlib.blake2b(blob, digest_size=16).digest()
        with self._lock:
            self._lru[key] = (thash, blob)
            cap = max(get_config().spec_cache_max_entries, 8)
            while len(self._lru) > cap:
                self._lru.popitem(last=False)
        return thash, blob

    @staticmethod
    def _delivered_set(client) -> set:
        """Hashes the peer has received ON THE CURRENT CONNECTION.  Keyed
        by writer identity: a reconnect installs a fresh writer, and the
        receiver interns process-globally, so stale entries only ever cause
        a redundant template resend, never a miss."""
        w = client._writer
        rec = getattr(client, "_raytpu_tmpl_sent", None)
        if rec is None or rec[0] is not w:
            rec = client._raytpu_tmpl_sent = (w, set())
        return rec[1]

    @staticmethod
    def forget_client(client) -> None:
        """Drop the delivered-set after a :class:`SpecCacheMiss` so the
        next encode resends full templates."""
        client._raytpu_tmpl_sent = None

    def encode(self, client, spec: TaskSpec):
        if not get_config().spec_cache_enabled or spec.is_actor_creation:
            return spec
        thash, blob = self._template_for(spec)
        sent = self._delivered_set(client)
        if thash in sent:
            tblob = None
        else:
            tblob = blob
            sent.add(thash)
        args = spec.args
        if isinstance(args, bytes) and len(args) >= _OOB_ARGS_MIN:
            args = pickle.PickleBuffer(args)
        return (_WIRE_TAG, thash, tblob, spec.task_id, args,
                spec.retry_count, spec.seq_no, spec.trace_ctx)


class SpecInterner:
    """Receiver side: process-global intern table hash -> prototype spec.
    Decoding clones the prototype (``__dict__`` copy) and stores the five
    volatile fields — no pickling of the invariant portion on warm
    submissions."""

    def __init__(self):
        self._lru: "collections.OrderedDict[bytes, TaskSpec]" = \
            collections.OrderedDict()

    def _intern(self, thash: bytes, tblob: bytes) -> TaskSpec:
        proto = TaskSpec.__new__(TaskSpec)
        fields = pickle.loads(tblob)
        proto.__dict__.update(fields)
        self._lru[thash] = proto
        cap = max(get_config().spec_cache_max_entries, 8)
        while len(self._lru) > cap:
            self._lru.popitem(last=False)
        return proto

    def decode(self, wire) -> TaskSpec:
        if isinstance(wire, TaskSpec):
            return wire
        if not (isinstance(wire, tuple) and len(wire) == 8
                and wire[0] == _WIRE_TAG):
            raise TypeError(f"not a task spec wire form: {type(wire)}")
        _tag, thash, tblob, task_id, args, retry_count, seq_no, trace_ctx = \
            wire
        proto = self._lru.get(thash)
        if proto is None:
            if tblob is None:
                raise SpecCacheMiss(
                    f"unknown spec template {thash.hex()[:16]} "
                    "(receiver cache evicted it?)")
            proto = self._intern(thash, tblob)
        else:
            self._lru.move_to_end(thash)
        spec = TaskSpec.__new__(TaskSpec)
        spec.__dict__.update(proto.__dict__)
        spec.task_id = task_id
        spec.args = args if isinstance(args, bytes) else bytes(args)
        spec.retry_count = retry_count
        spec.seq_no = seq_no
        spec.trace_ctx = trace_ctx
        spec.submitted_at = time.time()
        return spec


_interner: Optional[SpecInterner] = None


def interner() -> SpecInterner:
    global _interner
    if _interner is None:
        _interner = SpecInterner()
    return _interner


def decode(wire) -> TaskSpec:
    return interner().decode(wire)


def decode_many(wires) -> list:
    """Decode a batch, raising :class:`SpecCacheMiss` before any spec is
    acted on (the all-or-nothing contract the resend path relies on)."""
    it = interner()
    return [it.decode(w) for w in wires]
