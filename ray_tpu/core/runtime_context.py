"""Runtime context — ids and resources visible to running code.

Reference: ``python/ray/runtime_context.py`` (job/task/actor/node ids, assigned
resources).  Task-scoped fields use a contextvar set by the executor.
"""

from __future__ import annotations

import contextvars
from typing import Optional

_task_context: contextvars.ContextVar[dict] = contextvars.ContextVar(
    "raytpu_task_context", default=None)


class RuntimeContext:
    @property
    def _worker(self):
        from .core_worker import global_worker
        return global_worker()

    def get_job_id(self) -> str:
        ctx = _task_context.get()
        if ctx:
            return ctx["job_id"].hex()
        return self._worker.job_id.hex()

    def get_task_id(self) -> Optional[str]:
        ctx = _task_context.get()
        return ctx["task_id"].hex() if ctx else None

    def get_actor_id(self) -> Optional[str]:
        ctx = _task_context.get()
        if ctx and ctx.get("actor_id"):
            return ctx["actor_id"].hex()
        w = self._worker
        return w.actor_spec.actor_id.hex() if w.actor_spec else None

    def get_node_id(self) -> Optional[str]:
        return self._worker.node_id

    def get_worker_id(self) -> str:
        return self._worker.worker_id.hex()

    def get_assigned_resources(self) -> dict:
        """The resource amounts this task/actor was scheduled with
        (reference: ``runtime_context.get_assigned_resources``)."""
        ctx = _task_context.get()
        if ctx and "resources" in ctx:
            return dict(ctx["resources"]) or {"CPU": 1.0}
        w = self._worker
        if w.actor_spec is not None:
            return dict(w.actor_spec.resources or {}) or {"CPU": 1.0}
        return {}

    def get_accelerator_ids(self) -> dict:
        """Accelerator ids visible to this worker (reference:
        ``get_accelerator_ids``/``get_gpu_ids`` — here the TPU chips the
        scheduler granted, from TPU_VISIBLE_CHIPS)."""
        import os
        raw = os.environ.get("TPU_VISIBLE_CHIPS", "")
        return {"TPU": [c for c in raw.split(",") if c]}

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get(self) -> dict:
        return {"job_id": self.get_job_id(), "task_id": self.get_task_id(),
                "actor_id": self.get_actor_id(), "node_id": self.get_node_id(),
                "worker_id": self.get_worker_id()}


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
