"""Asyncio RPC: length-prefixed pickled messages over TCP.

Plays the role of the reference's gRPC wrapper layer (``src/ray/rpc/`` — ``grpc_server.h``,
``client_call.h``): every control-plane service (GCS-equivalent, node agents, workers)
exposes coroutine handlers on an :class:`RpcServer`; clients hold persistent connections
with request/response correlation, automatic reconnect, and call timeouts (reference:
retryable gRPC clients).  The wire format is ``4-byte length | pickle((req_id, method,
args))``; responses are ``(req_id, ok, payload)``.  Messages with ``req_id < 0`` are
one-way notifications (used by pubsub long-polls, reference ``src/ray/pubsub/``).

A single background event-loop thread per process hosts every server and client
(reference analogue: the single-threaded asio io_context per component,
``src/ray/common/asio/``) — this keeps handler code free of locks.
"""

from __future__ import annotations

import asyncio
import itertools
import pickle
import random
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, Optional

from . import chaos
from .chaos import ChaosFault
from .config import get_config

_loop_lock = threading.Lock()
# IO-loop LANES: lane 0 is the process's default background loop (the
# historical single "raytpu-io" thread every component shares); additional
# lanes are extra loop threads that carry their own subset of connections —
# the submission-lane / control-plane-lane substrate (ROADMAP item 5: one
# driver's submit path spread over multiple OS threads so socket syscalls,
# frame codecs and read loops overlap instead of serializing on one loop).
# Keys are small ints or short strings (("lane", i) tuples, "cp-gcs", ...).
_lanes: Dict[Any, tuple] = {}  # lane key -> (loop, thread)


def get_loop(lane: Any = 0) -> asyncio.AbstractEventLoop:
    """The process-wide background event loop for ``lane`` (started
    lazily).  ``get_loop()`` is the default lane every existing caller
    uses; other lanes are opt-in via the lane-aware clients."""
    with _loop_lock:
        ent = _lanes.get(lane)
        if ent is None or ent[0].is_closed():
            loop = asyncio.new_event_loop()
            started = threading.Event()

            def _run():
                asyncio.set_event_loop(loop)
                loop.call_soon(started.set)
                loop.run_forever()

            name = "raytpu-io" if lane == 0 else f"raytpu-io-{lane}"
            t = threading.Thread(target=_run, name=name, daemon=True)
            t.start()
            started.wait()
            _lanes[lane] = (loop, t)
        return _lanes[lane][0]


def run_async(coro, timeout: float | None = None, lane: Any = 0):
    """Run a coroutine on the IO loop of ``lane`` from a synchronous
    caller."""
    loop = get_loop(lane)
    if threading.current_thread() is _lanes[lane][1]:
        raise RuntimeError("run_async called from the IO loop thread (would deadlock)")
    fut = asyncio.run_coroutine_threadsafe(coro, loop)
    return fut.result(timeout)


# --------------------------------------------------------- RPC self-metrics
#
# Per-method client/server latency histograms, byte counters, an in-flight
# gauge and error counters (reference: grpc server/client interceptor stats
# feeding metric_defs.cc).  One lazy singleton per process — every RpcServer
# and RpcClient in the process shares it, and the regular registry flush
# ships it to the node agent's /metrics endpoint.  Disabled (config
# rpc_metrics_enabled=False) the hot path pays a single None check.

class _RpcMetrics:
    __slots__ = ("client_seconds", "server_seconds", "bytes_sent",
                 "bytes_received", "client_inflight", "errors", "reconnects",
                 "client_inflight_n", "_keys")

    def method_keys(self, method: str) -> tuple:
        """Precomputed sorted tag-key tuples for one method:
        (latency, client-bytes, server-bytes).  Built once per method —
        the hot path then calls the *_key metric fast paths instead of
        re-sorting a tags dict per frame."""
        k = self._keys.get(method)
        if k is None:
            k = self._keys[method] = (
                (("method", method),),
                (("method", method), ("role", "client")),
                (("method", method), ("role", "server")),
            )
        return k

    def __init__(self):
        from ray_tpu.util.metrics import Counter, Gauge, Histogram
        self.client_seconds = Histogram(
            "raytpu_rpc_client_seconds",
            "RPC client call latency (request sent -> response future done)",
            tag_keys=("method",))
        self.server_seconds = Histogram(
            "raytpu_rpc_server_seconds",
            "RPC server handler latency by method",
            tag_keys=("method",))
        self.bytes_sent = Counter(
            "raytpu_rpc_bytes_sent_total",
            "RPC frame bytes written, by method and side",
            tag_keys=("method", "role"))
        self.bytes_received = Counter(
            "raytpu_rpc_bytes_received_total",
            "RPC frame bytes read, by method and side",
            tag_keys=("method", "role"))
        self.client_inflight = Gauge(
            "raytpu_rpc_client_inflight",
            "RPC client calls awaiting a response in this process"
        ).set_fn(lambda: self.client_inflight_n)  # pull-based: zero hot-path cost
        self.errors = Counter(
            "raytpu_rpc_errors_total",
            "RPC failures by method, exception kind and side",
            tag_keys=("method", "kind", "role"))
        self.reconnects = Counter(
            "raytpu_rpc_reconnects_total",
            "client reconnections after a lost connection")
        self.client_inflight_n = 0
        self._keys: Dict[str, tuple] = {}


def _build_rpc_metrics():
    return _RpcMetrics() if get_config().rpc_metrics_enabled else None


_rpc_metrics_get: Optional[Callable[[], Optional[_RpcMetrics]]] = None


def rpc_metrics() -> Optional[_RpcMetrics]:
    global _rpc_metrics_get
    if _rpc_metrics_get is None:
        # the util.metrics import is deferred to FIRST CALL: at module
        # import time it would re-enter the ray_tpu package init (circular)
        from ray_tpu.util.metrics import lazy
        _rpc_metrics_get = lazy(_build_rpc_metrics)
    return _rpc_metrics_get()


def _encode(msg) -> bytes:
    payload = pickle.dumps(msg, protocol=5)
    if len(payload) >= 0x8000_0000:
        # The length word's top bit is the vectored-frame flag (_VEC_FLAG):
        # a >=2 GiB in-band payload would alias it and desync the stream.
        # Fail loudly — payloads that large must ship out-of-band.
        raise ValueError(f"frame payload too large ({len(payload)} B >= 2 GiB)")
    return len(payload).to_bytes(4, "big") + payload


# Vectored large-frame protocol: a frame whose length word has the top bit
# set carries out-of-band buffers after the pickle stream —
#
#   [4B VEC_FLAG | len(payload)] [payload] [4B nbufs] [8B hint]
#                                          [8B size]*nbufs [buf]*
#
# Large buffer-protocol payloads (object chunks, big inlined task args) ride
# as raw bytes instead of being re-copied through the pickle stream: the
# sender writes each buffer straight from its source memory (writev-style —
# see _flush_writer's large-part handling), and the receiver reads each into
# its own contiguous allocation and hands it to pickle out-of-band.  That
# removes one full-payload copy per side versus in-band pickling.
#
# ``hint`` is the reply's req_id (0 for requests/notifies): it lets the
# CLIENT route the first out-of-band buffer into a pre-registered
# destination view (``RpcClient.call_into`` — chunk pulls land readinto-
# style straight into the target shm segment, skipping the intermediate
# ``bytes`` materialization AND the slice-assign copy).  The req_id cannot
# serve this purpose from inside the payload: pickle.loads needs the
# buffers BEFORE it can surface the req_id.
_VEC_FLAG = 0x8000_0000
#: buffers below this stay in-band (framing + syscall overhead dominates)
_VEC_MIN_BUF = 256 * 1024
#: flush-queue parts at least this large are written individually (no join)
_LARGE_PART = 128 * 1024


def _encode_parts(msg, hint: int = 0) -> list:
    """Encode ``msg``, extracting large contiguous buffers out-of-band.
    Returns a list of wire parts (length 1 == a regular frame).  ``hint``
    rides the vectored header (the reply's req_id; see protocol note)."""
    bufs: list = []

    def _cb(pb: pickle.PickleBuffer):
        try:
            raw = pb.raw()
        except Exception:
            return True  # non-contiguous: serialize in-band
        if raw.nbytes < _VEC_MIN_BUF:
            return True
        bufs.append(raw)
        return False

    payload = pickle.dumps(msg, protocol=5, buffer_callback=_cb)
    if len(payload) >= _VEC_FLAG:
        raise ValueError(f"frame payload too large ({len(payload)} B >= 2 GiB)")
    if not bufs:
        return [len(payload).to_bytes(4, "big") + payload]
    head = ((_VEC_FLAG | len(payload)).to_bytes(4, "big") + payload
            + len(bufs).to_bytes(4, "big")
            + max(0, hint).to_bytes(8, "big")
            + b"".join(b.nbytes.to_bytes(8, "big") for b in bufs))
    return [head] + bufs


def coalesced_write(writer: "asyncio.StreamWriter", data: bytes) -> None:
    """Queue a frame and flush once per event-loop tick.

    One socket write per message was the top cost in PROFILE_CORE.md (53-68%
    of IO-loop samples in streams.write during tasks_async / n:n actors):
    every task submission, reply, and streamed result paid its own
    transport write.  Buffering frames and writing the concatenation on the
    next loop tick batches everything enqueued in the current tick into one
    syscall, preserving FIFO order PROVIDED every frame on a given writer
    goes through this function (mixing with direct writer.write would
    reorder).  Flow control: callers in coroutine context should
    ``await drain_if_needed(writer)`` after queueing.

    The FIRST frame of a tick writes through immediately (nothing is
    queued ahead of it, so FIFO holds): a single request/reply stops
    paying a +1-tick latency to an empty coalescing buffer — sequential
    RPC chains (sync task calls, the PG 2PC) were loop-tick-bound, not
    syscall-bound (ROADMAP 5).  A burst still batches frames 2..N of the
    tick into one write."""
    buf = getattr(writer, "_raytpu_buf", None)
    if buf is None:
        buf = writer._raytpu_buf = []
        writer._raytpu_buf_bytes = 0
    if not buf and not getattr(writer, "_raytpu_flush_scheduled", False):
        writer._raytpu_flush_scheduled = True
        asyncio.get_event_loop().call_soon(_flush_writer, writer)
        try:
            writer.write(data)
        except Exception:
            pass  # connection died; the read loop surfaces it
        return
    buf.append(data)
    writer._raytpu_buf_bytes += len(data)
    if not getattr(writer, "_raytpu_flush_scheduled", False):
        writer._raytpu_flush_scheduled = True
        asyncio.get_event_loop().call_soon(_flush_writer, writer)


def coalesced_write_frame(writer: "asyncio.StreamWriter", msg,
                          hint: int = 0) -> int:
    """Encode + queue one message, using the vectored wire format when the
    payload carries large buffers.  Vectored frames flush IMMEDIATELY (in
    FIFO order with everything already queued): their out-of-band parts are
    views over caller memory that must not dangle across a loop tick, and a
    multi-MB frame gains nothing from coalescing anyway.  Returns the wire
    bytes queued (the RPC byte counters' data source)."""
    parts = _encode_parts(msg, hint)
    if len(parts) == 1:
        coalesced_write(writer, parts[0])
        return len(parts[0])
    buf = getattr(writer, "_raytpu_buf", None)
    if buf is None:
        buf = writer._raytpu_buf = []
        writer._raytpu_buf_bytes = 0
    nbytes = sum(len(p) for p in parts)
    buf.extend(parts)
    writer._raytpu_buf_bytes += nbytes
    _flush_writer(writer)
    return nbytes


def _flush_writer(writer: "asyncio.StreamWriter") -> None:
    writer._raytpu_flush_scheduled = False
    buf = getattr(writer, "_raytpu_buf", None)
    if not buf:
        return
    parts = list(buf)
    buf.clear()
    writer._raytpu_buf_bytes = 0
    try:
        if len(parts) == 1:
            writer.write(parts[0])
            return
        # Small frames coalesce into one write; large parts (vectored
        # buffers) are written individually so a multi-MB payload never
        # pays a user-space concatenation — the socket layer copies it
        # straight from the source view into the kernel.
        run: list = []
        for p in parts:
            if len(p) >= _LARGE_PART:
                if run:
                    writer.write(b"".join(run))
                    run = []
                writer.write(p)
            else:
                run.append(p)
        if run:
            writer.write(b"".join(run) if len(run) > 1 else run[0])
    except Exception:
        pass  # connection died; the read loop surfaces it


async def drain_if_needed(writer: "asyncio.StreamWriter",
                          high_water: int = 1 << 20) -> None:
    """Apply backpressure only when the transport buffer is actually deep —
    an unconditional drain() per frame defeats the coalescing.  Pending
    coalesced frames still sit in the Python-level buffer until the next
    loop tick, so they must count toward the high-water mark: a coroutine
    emitting many frames without a real await never yields to the loop,
    and the transport alone would read as empty forever."""
    try:
        pending = getattr(writer, "_raytpu_buf_bytes", 0)
        if (pending + writer.transport.get_write_buffer_size()) > high_water:
            _flush_writer(writer)
            await writer.drain()
    except Exception:
        pass


class _OobSink:
    """A registered destination for one reply's out-of-band buffer (see
    ``RpcClient.call_into``).  ``done`` is set once the read loop has
    finished (or abandoned) landing into ``view`` — the caller's cleanup
    awaits it so no late frame can write into memory the caller is about
    to recycle."""

    __slots__ = ("view", "started", "done")

    def __init__(self, view: memoryview):
        self.view = view
        self.started = False
        self.done = asyncio.Event()


async def _read_buffer_into(reader: asyncio.StreamReader,
                            view: memoryview) -> None:
    """readinto-style exact read: drain the stream buffer DIRECTLY into
    ``view`` (one copy) instead of materializing an intermediate ``bytes``
    and slice-assigning it (two copies).  Uses StreamReader's internal
    buffer the same way readexactly does; falls back to readexactly+copy
    if the internals ever change shape."""
    n = view.nbytes
    buf = getattr(reader, "_buffer", None)
    if buf is None or not hasattr(reader, "_wait_for_data") \
            or not hasattr(reader, "_maybe_resume_transport"):
        view[:] = await reader.readexactly(n)
        return
    pos = 0
    while pos < n:
        exc = reader.exception()
        if exc is not None:
            raise exc
        if buf:
            take = min(len(buf), n - pos)
            with memoryview(buf) as mv:
                view[pos:pos + take] = mv[:take]
            del buf[:take]
            reader._maybe_resume_transport()
            pos += take
            continue
        if reader.at_eof():
            raise asyncio.IncompleteReadError(b"", n)
        await reader._wait_for_data("_read_buffer_into")


async def _read_msg(reader: asyncio.StreamReader,
                    sinks: Optional[Dict[int, _OobSink]] = None):
    """-> (message, wire_bytes) for one frame.

    ``sinks`` (client side only): req_id -> _OobSink.  When a vectored
    reply's hint matches a registered sink, its first out-of-band buffer
    is landed readinto-style straight into the sink view and that view is
    handed to pickle — zero-extra-copy receive for chunk pulls."""
    hdr = await reader.readexactly(4)
    n = int.from_bytes(hdr, "big")
    if not n & _VEC_FLAG:
        return pickle.loads(await reader.readexactly(n)), 4 + n
    # Vectored frame: pickle stream + out-of-band buffers.  Each buffer is
    # read into its own allocation (or the registered sink) and handed to
    # pickle out-of-band — in-band pickling would pay an extra copy
    # materializing the bytes out of the stream.
    plen = n & (_VEC_FLAG - 1)
    payload = await reader.readexactly(plen)
    nbufs = int.from_bytes(await reader.readexactly(4), "big")
    hint = int.from_bytes(await reader.readexactly(8), "big")
    sizes_raw = await reader.readexactly(8 * nbufs)
    bufs = []
    total = 16 + plen + 8 * nbufs
    entry = sinks.pop(hint, None) if (sinks is not None and hint) else None
    try:
        for i in range(nbufs):
            size = int.from_bytes(sizes_raw[8 * i:8 * i + 8], "big")
            if entry is not None and size <= entry.view.nbytes:
                entry.started = True
                try:
                    target = entry.view[:size]
                    await _read_buffer_into(reader, target)
                    bufs.append(target)
                finally:
                    entry.done.set()
                entry = None
            else:
                bufs.append(await reader.readexactly(size))
            total += size
    finally:
        if entry is not None:  # popped but unused (size mismatch)
            entry.done.set()
    return pickle.loads(payload, buffers=bufs), total


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class TransientServerError(RpcError):
    """Handler-raised transient failure with DROP-from-cache semantics:
    the reply is an error, but the idempotency entry for the call's token
    is removed instead of recorded — a same-token retry RE-EXECUTES the
    handler rather than replaying a stale error (used e.g. for lease
    grants that completed after the requester's connection died; the
    retry arrives on a live connection and deserves a fresh grant).
    ``call_retry`` treats it as retryable."""


class RemoteError(RpcError):
    """Handler raised; carries the remote traceback string."""

    def __init__(self, cause: BaseException, tb: str):
        super().__init__(f"{type(cause).__name__}: {cause}\n--- remote traceback ---\n{tb}")
        self.cause = cause
        self.remote_traceback = tb

    def __reduce__(self):
        # Default exception reduce would replay __init__ with the formatted
        # message only (TypeError on unpickle) — rebuild from the real parts
        # so a RemoteError inside a shipped task-error blob round-trips.
        return (RemoteError, (self.cause, self.remote_traceback))


class _BusyTimed:
    """Await a coroutine while accumulating the duration of each of its
    SYNCHRONOUS segments (the stretches between suspension points) into
    ``acc[0]``.

    Driving the inner coroutine's ``__await__`` iterator by hand lets the
    wrapper clock every ``send``/``throw`` — so a handler that parks 30 s
    in a long-poll attributes only the slivers it actually ran, while a
    handler that pickles a 10 MB table attributes all of it.  That
    distinction is the whole point: wall-time histograms
    (raytpu_rpc_server_seconds) can't tell "slow because busy" from
    "slow because waiting".  Segments are timed with ``perf_counter``,
    not the thread-CPU clock: a synchronous segment monopolizes the event
    loop for its full wall duration (GIL waits included), and that —
    "how long did this handler block the loop" — is the saturation
    signal; the thread-CPU clock also ticks too coarsely (10 ms on some
    kernels) to see microsecond handlers at all."""

    __slots__ = ("coro", "acc")

    def __init__(self, coro, acc):
        self.coro = coro
        self.acc = acc

    def __await__(self):
        it = self.coro.__await__()
        acc = self.acc
        val, exc = None, None
        while True:
            t0 = time.perf_counter()
            try:
                if exc is not None:
                    e, exc = exc, None
                    y = it.throw(e)
                else:
                    y = it.send(val)
            except StopIteration as e:
                acc[0] += time.perf_counter() - t0
                return e.value
            except BaseException:
                acc[0] += time.perf_counter() - t0
                raise
            acc[0] += time.perf_counter() - t0
            try:
                val = yield y
            except BaseException as e:  # noqa: BLE001 — forwarded inward
                val, exc = None, e


class RpcServer:
    """Dispatches ``(req_id, method, kwargs)`` to ``handler.handle_<method>`` coroutines."""

    #: idempotency-cache ceilings (entries AND approximate bytes — large
    #: cached replies, e.g. token'd actor_task inline results, must not
    #: pool hundreds of MB for the whole dedup window)
    IDEM_CACHE_MAX = 4096
    IDEM_CACHE_MAX_BYTES = 64 << 20

    def __init__(self, handler: Any, host: str = "127.0.0.1", port: int = 0,
                 bulk_replies: bool = False):
        self.handler = handler
        self.host = host
        self.port = port
        #: servers that stream multi-MB reply frames (node agents serving
        #: read_chunk) raise SO_SNDBUF on every accepted connection — a
        #: buffer CAP, not committed memory — so a vectored chunk reply
        #: moves in a few large sends instead of dozens of partial ones
        self.bulk_replies = bulk_replies
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[asyncio.StreamWriter] = set()
        #: optional per-handler BUSY-seconds attribution callback
        #: ``(method, busy_s) -> None`` — when set (the GCS does, behind
        #: sched_metrics_enabled), each dispatch drives the handler
        #: coroutine through ``_BusyTimed`` and reports the time its
        #: synchronous segments blocked the loop (awaits excluded), the
        #: signal that names which handler is eating the event loop.
        self.busy_cb = None
        # Idempotency dedup window (reference: exactly-once semantics for
        # retried mutating RPCs): token -> (expiry, in-flight future |
        # (ok, result), approx_bytes).  A retry carrying a token already
        # seen replays the recorded reply — or awaits the original
        # execution still in flight — instead of re-running the handler.
        self._idem: Dict[str, tuple] = {}
        self._idem_bytes = 0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def start(self):
        # 16 MB stream buffer: the default 64 KB limit makes readexactly of
        # multi-MB frames (object chunks) crawl through hundreds of tiny
        # transport reads with pause/resume churn.
        self._server = await asyncio.start_server(self._on_conn, self.host,
                                                  self.port, limit=16 << 20)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    def start_sync(self) -> "RpcServer":
        return run_async(self.start())

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._conns.add(writer)
        if self.bulk_replies:
            try:
                import socket as _socket
                sock = writer.get_extra_info("socket")
                if sock is not None:
                    sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF,
                                    RpcClient.BULK_SOCK_BUF)
            except Exception:
                pass
        peer = writer.get_extra_info("peername")
        if hasattr(self.handler, "on_connect"):
            await self.handler.on_connect(peer, writer)
        try:
            while True:
                try:
                    (req_id, method, kwargs), nbytes = await _read_msg(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                m = rpc_metrics()
                if m is not None:
                    m.bytes_received.inc_key(m.method_keys(method)[2],
                                             nbytes)
                # Handle each request concurrently so a slow handler (e.g. a
                # blocking Get) doesn't head-of-line-block the connection.
                asyncio.ensure_future(self._dispatch(writer, req_id, method, kwargs))
        finally:
            self._conns.discard(writer)
            if hasattr(self.handler, "on_disconnect"):
                try:
                    await self.handler.on_disconnect(peer, writer)
                except Exception:
                    pass
            try:
                writer.close()
            except Exception:
                pass

    @classmethod
    def _approx_result_bytes(cls, result, _depth: int = 3) -> int:
        """Cheap size estimate for a cached reply: count bytes-like
        payloads (the only members that can be large) a few levels deep —
        actor replies are LISTS of ('inline', bytes, ...) tuples, so one
        level would miss every inline payload."""
        if isinstance(result, (bytes, bytearray, memoryview)):
            return len(result)
        n = 64
        if _depth > 0 and isinstance(result, (tuple, list)):
            for el in result:
                n += cls._approx_result_bytes(el, _depth - 1)
        return n

    def _idem_pop(self, tok: str):
        ent = self._idem.pop(tok, None)
        if ent is not None:
            self._idem_bytes -= ent[2]

    def _idem_store(self, tok: str, entry, nbytes: int):
        old = self._idem.get(tok)
        if old is not None:
            self._idem_bytes -= old[2]
        self._idem[tok] = (
            time.monotonic() + get_config().rpc_dedup_window_s, entry, nbytes)
        self._idem_bytes += nbytes

    def _prune_idem(self):
        # Amortized front-of-dict expiry: insertion order == arrival order
        # (value replacement keeps a key's position), so expired entries
        # cluster at the front.  Keeps the cache sized to the live window
        # instead of letting big cached results pool until the ceiling.
        now = time.monotonic()
        while self._idem:
            tok = next(iter(self._idem))
            exp, entry, _n = self._idem[tok]
            if exp < now and not isinstance(entry, asyncio.Future):
                self._idem_pop(tok)
            else:
                break
        # Hard ceilings (entries and bytes) regardless of expiry — but
        # never evict an IN-FLIGHT future: a same-token retry racing the
        # evicted original would re-execute the mutating handler
        # concurrently, the exact double-apply this cache prevents.
        if (len(self._idem) > self.IDEM_CACHE_MAX
                or self._idem_bytes > self.IDEM_CACHE_MAX_BYTES):
            for tok in list(self._idem):
                if (len(self._idem) <= self.IDEM_CACHE_MAX
                        and self._idem_bytes <= self.IDEM_CACHE_MAX_BYTES):
                    break
                if not isinstance(self._idem[tok][1], asyncio.Future):
                    self._idem_pop(tok)

    async def _dispatch(self, writer, req_id, method, kwargs):
        m = rpc_metrics()
        t0 = time.monotonic() if m is not None else 0.0
        inj = chaos.injector()
        token = kwargs.pop("_idem", None)
        cached = False
        inflight = None
        if token is not None:
            hit = self._idem.get(token)
            if hit is not None:
                entry = hit[1]
                if isinstance(entry, asyncio.Future):
                    # original execution still in flight (its reply was
                    # lost): piggyback on it — the handler runs ONCE
                    ok, result = await asyncio.shield(entry)
                else:
                    ok, result = entry
                cached = True
        if not cached:
            if (inj is not None and req_id >= 0
                    and inj.should("fail_before", method)):
                # fail-before-commit: the handler never ran; blind retry
                # is safe, so no dedup entry is recorded
                ok = False
                result = (ChaosFault(f"chaos: {method} failed before "
                                     "execution"), "")
            else:
                if token is not None:
                    inflight = asyncio.get_event_loop().create_future()
                    self._idem_store(token, inflight, 256)
                    self._prune_idem()
                try:
                    fn = getattr(self.handler, "handle_" + method)
                    if getattr(fn, "rpc_pass_writer", False):
                        # Handler streams interim server->client pushes on
                        # this connection (req_id -1 frames; the client
                        # routes them to its on_push handler) before the
                        # final reply.
                        kwargs["_writer"] = writer
                    if self.busy_cb is not None:
                        acc = [0.0]
                        try:
                            result = await _BusyTimed(fn(**kwargs), acc)
                        finally:
                            try:
                                self.busy_cb(method, acc[0])
                            except Exception:
                                pass
                    else:
                        result = await fn(**kwargs)
                    ok = True
                except BaseException as e:  # noqa: BLE001 — errors travel back
                    result = (e, traceback.format_exc())
                    ok = False
                    if m is not None:
                        m.errors.inc(tags={"method": method,
                                           "kind": type(e).__name__,
                                           "role": "server"})
                if inflight is not None:
                    if not ok and isinstance(result[0], TransientServerError):
                        # drop-from-cache semantics: waiters piggybacked on
                        # THIS execution see the error once, but a later
                        # same-token retry re-executes instead of
                        # replaying a stale transient failure
                        self._idem_pop(token)
                    else:
                        # the COMMITTED outcome — recorded before any
                        # chaos mangles the reply, so a retry observes it
                        self._idem_store(token, (ok, result),
                                         self._approx_result_bytes(result))
                    inflight.set_result((ok, result))
                if (inj is not None and ok and req_id >= 0
                        and inj.should("fail_after", method)):
                    # fail-after-commit: state changed, reply replaced by
                    # an error — only an idempotent retry survives this
                    ok = False
                    result = (ChaosFault(f"chaos: {method} failed after "
                                         "execution"), "")
        if m is not None:
            m.server_seconds.observe_key(m.method_keys(method)[0],
                                         time.monotonic() - t0)
        if req_id >= 0:
            if (inj is not None and not cached
                    and inj.should("drop_reply", method)):
                # a lost reply on a live TCP stream == the link dying:
                # abort so the client fails fast and retries
                try:
                    writer.transport.abort()
                except Exception:
                    pass
                return
            try:
                try:
                    # hint=req_id lets the client land this reply's
                    # out-of-band buffer into a pre-registered sink
                    n = coalesced_write_frame(writer, (req_id, ok, result),
                                              hint=req_id)
                except (ConnectionResetError, BrokenPipeError):
                    return
                except Exception:
                    # Unpicklable result/exception: degrade to a picklable
                    # error so the caller fails fast instead of timing out.
                    err = RuntimeError(
                        f"handler {method!r} produced an unpicklable "
                        f"{'result' if ok else 'exception'}: {result!r:.500}")
                    n = coalesced_write_frame(writer, (req_id, False, (err, "")))
                if m is not None:
                    m.bytes_sent.inc_key(m.method_keys(method)[2], n)
                await drain_if_needed(writer)
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def stop(self):
        # Close live connections BEFORE wait_closed: since 3.12 wait_closed
        # blocks until every connection handler returns, and long-poll
        # clients (pubsub, heartbeats) would keep theirs open forever.
        if self._server:
            self._server.close()
        for w in list(self._conns):
            try:
                w.close()
            except Exception:
                pass
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except Exception:
                pass

    def stop_sync(self):
        try:
            run_async(self.stop(), timeout=5)
        except Exception:
            pass


class RpcClient:
    """Persistent connection to one RpcServer; safe to share across coroutines.

    ``lane`` pins this client's connection, read loop, and frame codecs to
    a specific IO-loop thread (``get_loop(lane)``).  Lane-0 clients (the
    default) keep the historical behavior — their coroutines run on
    whatever loop awaits them.  Laned clients trampoline foreign-loop
    callers onto their home lane (``run_coroutine_threadsafe``), so the
    per-frame pickle/unpickle and socket syscalls of different connections
    land on different OS threads — the owner submission-lane substrate."""

    #: socket tuning applied to BULK (transfer-stripe) connections: big
    #: kernel buffers (caps, not committed memory) let an 8 MB reply
    #: frame move with far fewer partial sends, and a larger per-wakeup
    #: read size cuts the receiver's syscall + loop-iteration count per
    #: chunk.  Only dedicated transfer connections get this — on a
    #: control-plane connection a multi-MB recv allocation per 100-byte
    #: frame would be pure waste.
    BULK_SOCK_BUF = 8 << 20
    BULK_READ_SIZE = 2 << 20

    def __init__(self, address: str, lane: Any = 0, bulk: bool = False):
        self.address = address
        self._lane = lane
        self._bulk = bulk
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        # Pending futures are PER CONNECTION: each connection gets a fresh
        # dict whose read loop is the only popper, and whose teardown fails
        # exactly the futures that rode that connection.  A process-wide
        # dict had a race: _read_loop's finally cleared it while a
        # call_start parked at an await (chaos delay) could still insert —
        # that call then hung to its full timeout instead of failing fast.
        self._pending: Dict[int, asyncio.Future] = {}
        #: req_id -> _OobSink, per connection like _pending: registered
        #: destination views for replies' out-of-band buffers (call_into)
        self._sinks: Dict[int, _OobSink] = {}
        self._req_ids = itertools.count(1)
        self._connect_lock: asyncio.Lock | None = None
        self._closed = False
        self._connected_once = False
        self._push_handler: Callable[[str, dict], None] | None = None

    def on_push(self, fn: Callable[[str, dict], None]):
        """Register a callback for server-initiated one-way messages.
        On a laned client the callback fires on the LANE's loop thread —
        handlers that touch loop-0-confined state must hop themselves."""
        self._push_handler = fn

    def _foreign_home(self) -> Optional[asyncio.AbstractEventLoop]:
        """The home-lane loop when the caller is on a different loop (or
        no loop); None for lane-0 clients and on-lane callers — the
        zero-overhead common case is one int compare."""
        if self._lane == 0:
            return None
        home = get_loop(self._lane)
        try:
            if asyncio.get_running_loop() is home:
                return None
        except RuntimeError:
            pass
        return home

    async def ensure_connected(self):
        """Public connect (lane-aware): laned clients connect on their
        home lane so the connection's read loop lives there."""
        home = self._foreign_home()
        if home is not None:
            return await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                self._ensure_connected(), home))
        return await self._ensure_connected()

    async def _ensure_connected(self):
        if self._connect_lock is None:
            self._connect_lock = asyncio.Lock()
        async with self._connect_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            cfg = get_config()
            self._reader, self._writer = await asyncio.wait_for(
                asyncio.open_connection(self._host, self._port,
                                        limit=16 << 20),
                timeout=cfg.rpc_connect_timeout_s)
            if self._bulk:
                try:
                    import socket as _socket
                    sock = self._writer.get_extra_info("socket")
                    if sock is not None:
                        sock.setsockopt(_socket.SOL_SOCKET,
                                        _socket.SO_SNDBUF,
                                        self.BULK_SOCK_BUF)
                        sock.setsockopt(_socket.SOL_SOCKET,
                                        _socket.SO_RCVBUF,
                                        self.BULK_SOCK_BUF)
                    self._writer.transport.max_size = self.BULK_READ_SIZE
                except Exception:
                    pass
            self._pending = {}
            self._sinks = {}
            if self._connected_once:
                m = rpc_metrics()
                if m is not None:
                    m.reconnects.inc()
            self._connected_once = True
            asyncio.ensure_future(
                self._read_loop(self._reader, self._writer, self._pending,
                                self._sinks))

    async def _read_loop(self, reader, writer, pending, sinks):
        try:
            while True:
                msg, nbytes = await _read_msg(reader, sinks)
                req_id, ok, payload = msg
                if req_id < 0:  # server push
                    if self._push_handler:
                        try:
                            self._push_handler(ok, payload)  # ok field carries topic
                        except Exception:
                            traceback.print_exc()
                    continue
                fut = pending.pop(req_id, None)
                if fut is not None:
                    m = rpc_metrics()
                    if m is not None:
                        method = getattr(fut, "_raytpu_method", "?")
                        m.bytes_received.inc_key(m.method_keys(method)[1],
                                                 nbytes)
                    if not fut.done():
                        if ok:
                            fut.set_result(payload)
                        else:
                            cause, tb = payload
                            fut.set_exception(RemoteError(cause, tb))
        except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError):
            pass
        finally:
            # Tear down only THIS connection's state: a reconnect may
            # already have installed a fresh writer/pending pair.
            if self._writer is writer:
                self._writer = None
            try:
                writer.close()
            except Exception:
                pass
            err = ConnectionLost(f"connection to {self.address} lost")
            for fut in pending.values():
                if not fut.done():
                    fut.set_exception(err)
            pending.clear()
            # never-consumed sinks can't be written anymore: release any
            # call_into cleanup parked on them
            for entry in sinks.values():
                entry.done.set()
            sinks.clear()

    def _chaos_pre(self, method: str):
        """Client-side chaos consultation for one outbound frame:
        -> (injector, added delay).  Raises ConnectionLost on partition."""
        inj = chaos.injector()
        d = 0.0
        if inj is not None:
            if inj.should("partition", method, self.address):
                raise ConnectionLost(
                    f"chaos: link to {self.address} partitioned")
            d = inj.delay_s(method, self.address)
        return inj, d

    def _chaos_drop_frame(self, writer):
        """A chaos-dropped frame on a live TCP stream is indistinguishable
        from the link dying: abort the connection so every pending call on
        it fails fast with ConnectionLost instead of hanging to timeout."""
        try:
            writer.transport.abort()
        except Exception:
            try:
                writer.close()
            except Exception:
                pass

    async def call_start(self, method: str, _oob_sink=None,
                         **kwargs) -> "asyncio.Future":
        """Issue the request and return its response future without awaiting it.
        Successive call_start invocations hit the server in program order —
        used for actor-call sequencing (reference: per-handle sequence numbers
        in CoreWorkerDirectActorTaskSubmitter).

        ``_oob_sink`` (a writable memoryview) registers a destination for
        the reply's first out-of-band buffer: the read loop lands it there
        readinto-style (see call_into), and the reply object pickle returns
        is a view over that memory."""
        if self._closed:
            raise RpcError("client closed")
        if self._foreign_home() is not None:
            # call_start hands back a future bound to ONE loop; awaiting
            # it from another loop is undefined — laned clients must be
            # driven via call/call_retry/notify from foreign loops.
            raise RuntimeError(
                "call_start on a laned RpcClient from a foreign loop "
                "(use call/call_retry, which trampoline)")
        inj, delay = self._chaos_pre(method)
        await self._ensure_connected()
        writer, pending, sinks = self._writer, self._pending, self._sinks
        if delay > 0.0:
            await asyncio.sleep(delay)
            # the connection may have died (or been replaced) during the
            # sleep — fail fast rather than enqueueing on a dead link
            if self._writer is not writer or writer is None \
                    or writer.is_closing():
                raise ConnectionLost(
                    f"connection to {self.address} lost before send")
        req_id = next(self._req_ids)
        fut = asyncio.get_event_loop().create_future()
        pending[req_id] = fut
        if _oob_sink is not None:
            entry = _OobSink(_oob_sink)
            sinks[req_id] = entry
            fut._raytpu_sink = (sinks, req_id, entry, writer)
        if inj is not None and inj.should("drop_request", method,
                                          self.address):
            nbytes = 0
        else:
            nbytes = coalesced_write_frame(writer, (req_id, method, kwargs))
        m = rpc_metrics()
        if m is not None:
            keys = m.method_keys(method)
            fut._raytpu_method = method
            m.bytes_sent.inc_key(keys[1], nbytes)
            m.client_inflight_n += 1
            t0 = time.monotonic()

            def _done(f, _m=m, _method=method, _lat_key=keys[0], _t0=t0):
                _m.client_inflight_n -= 1
                _m.client_seconds.observe_key(_lat_key,
                                              time.monotonic() - _t0)
                if f.cancelled():
                    kind = "cancelled"  # usually the caller's timeout
                else:
                    exc = f.exception()  # retrieves it: no GC-time warning
                    kind = type(exc).__name__ if exc is not None else None
                if kind:
                    _m.errors.inc(tags={"method": _method, "kind": kind,
                                        "role": "client"})

            fut.add_done_callback(_done)
        if nbytes == 0:
            # dropped frame: kill the link so this (and every pending)
            # call surfaces ConnectionLost promptly
            self._chaos_drop_frame(writer)
            return fut
        await drain_if_needed(writer)
        return fut

    async def call(self, method: str, _timeout: float | None = None, **kwargs) -> Any:
        home = self._foreign_home()
        if home is not None:
            return await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                self.call(method, _timeout=_timeout, **kwargs), home))
        fut = await self.call_start(method, **kwargs)
        timeout = _timeout if _timeout is not None else get_config().rpc_call_timeout_s
        return await asyncio.wait_for(fut, timeout)

    async def call_into(self, method: str, sink: memoryview,
                        _timeout: float | None = None, **kwargs) -> Any:
        """``call`` whose reply's out-of-band buffer lands DIRECTLY into
        ``sink`` (zero-extra-copy receive: stream buffer -> sink, no
        intermediate bytes, no slice-assign).  The returned value for an
        out-of-band reply is a (readonly) memoryview over ``sink``; small
        in-band replies still return bytes the caller must place itself.

        The finally block guarantees that once this coroutine returns — by
        result, error, timeout or cancellation — NO late frame can write
        into ``sink``: the registration is withdrawn, or a landing already
        in progress is awaited to completion.  Callers may recycle the
        memory behind ``sink`` immediately after."""
        fut = await self.call_start(method, _oob_sink=sink, **kwargs)
        timeout = (_timeout if _timeout is not None
                   else get_config().rpc_call_timeout_s)
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            info = getattr(fut, "_raytpu_sink", None)
            if info is not None:
                sinks, req_id, entry, writer = info
                if sinks.get(req_id) is entry:
                    del sinks[req_id]  # read loop never took it: safe now
                elif entry.started and not entry.done.is_set():
                    # landing in progress on the read loop: wait it out so
                    # the caller can recycle the sink's memory
                    try:
                        await asyncio.wait_for(entry.done.wait(), 30.0)
                    except asyncio.TimeoutError:
                        # a landing wedged mid-stream for 30 s: kill the
                        # connection so the read loop aborts NOW — the
                        # no-late-write guarantee must hold even here
                        # (the caller may recycle an arena range next)
                        try:
                            writer.transport.abort()
                        except Exception:
                            pass
                        try:
                            await asyncio.wait_for(entry.done.wait(), 10.0)
                        except asyncio.TimeoutError:
                            pass

    async def call_retry(self, method: str, _timeout: float | None = None,
                         _attempts: int | None = None,
                         _idempotent: bool = True, **kwargs) -> Any:
        """Retrying call for transient transport faults (reference:
        retryable gRPC clients).  Bounded attempts with exponential backoff
        + full jitter, all under ONE shared deadline (`_timeout`, default
        ``rpc_call_timeout_s``) that propagates into each attempt's
        per-call timeout.

        With ``_idempotent=True`` (the default) a client-stamped
        idempotency token rides every attempt: the server's dedup window
        replays the committed reply for a retry instead of re-executing
        the handler, so retried MUTATING RPCs (register_actor, kv_put,
        lease grants/returns, pin grants) apply exactly once.  Pass
        ``_idempotent=False`` for read-only calls to skip the server-side
        cache entry (re-executing a read is free).

        Retries on: ConnectionLost / OSError (link died), TimeoutError
        with deadline remaining, and ChaosFault RemoteErrors (injected
        failures are retryable by definition).  Application errors
        propagate immediately."""
        home = self._foreign_home()
        if home is not None:
            # the whole retry loop (backoff sleeps included) runs on the
            # home lane; the caller just awaits its outcome
            return await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                self.call_retry(method, _timeout=_timeout,
                                _attempts=_attempts,
                                _idempotent=_idempotent, **kwargs), home))
        cfg = get_config()
        attempts = (_attempts if _attempts is not None
                    else cfg.rpc_retry_max_attempts)
        total = _timeout if _timeout is not None else cfg.rpc_call_timeout_s
        deadline = time.monotonic() + total
        if _idempotent:
            kwargs["_idem"] = uuid.uuid4().hex
        last: Optional[BaseException] = None
        for attempt in range(max(1, attempts)):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                return await self.call(method, _timeout=remaining, **kwargs)
            except (ConnectionLost, ConnectionError, OSError,
                    asyncio.TimeoutError) as e:
                last = e
            except RemoteError as e:
                if not isinstance(e.cause, (ChaosFault, TransientServerError)):
                    raise
                last = e
            if self._closed or attempt >= attempts - 1:
                break  # no backoff after the FINAL attempt — nothing follows
            step = min(cfg.rpc_retry_max_delay_s,
                       cfg.rpc_retry_base_delay_s * (2 ** attempt))
            sleep = min(random.uniform(0, step),
                        max(0.0, deadline - time.monotonic()))
            if sleep > 0:
                await asyncio.sleep(sleep)
        if last is not None:
            raise last
        raise asyncio.TimeoutError(
            f"{method}: deadline exhausted before first attempt")

    async def notify(self, method: str, **kwargs):
        home = self._foreign_home()
        if home is not None:
            return await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                self.notify(method, **kwargs), home))
        inj, delay = self._chaos_pre(method)
        await self._ensure_connected()
        writer = self._writer
        if delay > 0.0:
            await asyncio.sleep(delay)
            if self._writer is not writer or writer is None \
                    or writer.is_closing():
                raise ConnectionLost(
                    f"connection to {self.address} lost before send")
        if inj is not None and inj.should("drop_request", method,
                                          self.address):
            self._chaos_drop_frame(writer)
            return
        nbytes = coalesced_write_frame(writer, (-1, method, kwargs))
        m = rpc_metrics()
        if m is not None:
            m.bytes_sent.inc_key(m.method_keys(method)[1], nbytes)
        await drain_if_needed(writer)

    def call_sync(self, method: str, _timeout: float | None = None, **kwargs) -> Any:
        return run_async(self.call(method, _timeout=_timeout, **kwargs),
                         timeout=(_timeout or get_config().rpc_call_timeout_s) + 5)

    async def close(self):
        self._closed = True
        home = self._foreign_home()
        if home is not None:
            # flush + transport close must run on the loop that owns the
            # connection
            return await asyncio.wrap_future(asyncio.run_coroutine_threadsafe(
                self._close_local(), home))
        await self._close_local()

    async def _close_local(self):
        if self._writer:
            try:
                _flush_writer(self._writer)  # don't drop coalesced frames
                self._writer.close()
            except Exception:
                pass
            self._writer = None


class ClientPool:
    """Cache of RpcClients keyed by address (reference: rpc client pools).

    ``push_handler(topic, payload)``, when given, is installed on every
    client so server-initiated pushes (streamed task results) are routed.

    ``lanes > 1`` spreads addresses over that many IO-loop threads
    (sticky: an address keeps its lane for the pool's lifetime, so
    per-connection ordering — actor seq_nos, streamed yields — is
    unchanged; lane index 0 is the default loop, the rest are dedicated
    submission-lane threads).  Push handlers fire on the owning lane's
    thread — pass a thread-safe handler when lanes > 1."""

    def __init__(self, push_handler: Callable[[str, dict], None] | None = None,
                 lanes: int = 1):
        self._clients: Dict[str, RpcClient] = {}
        self._push_handler = push_handler
        self._num_lanes = max(1, int(lanes))
        self._lane_rr = 0
        self._lane_of: Dict[str, Any] = {}

    def _lane_for(self, address: str) -> Any:
        if self._num_lanes <= 1:
            return 0
        lane = self._lane_of.get(address)
        if lane is None:
            i = self._lane_rr % self._num_lanes
            self._lane_rr += 1
            lane = 0 if i == 0 else ("lane", i)
            self._lane_of[address] = lane
        return lane

    def get(self, address: str) -> RpcClient:
        c = self._clients.get(address)
        if c is None or c._closed:
            c = RpcClient(address, lane=self._lane_for(address))
            if self._push_handler is not None:
                c.on_push(self._push_handler)
            self._clients[address] = c
        return c

    def get_striped(self, address: str, stripe: int) -> RpcClient:
        """A PARALLEL connection to ``address``: stripe 0 is the pool's
        regular client, stripes >= 1 are extra sockets cached under a
        derived key (the bulk-transfer substrate: multi-MB reply frames
        to one peer stream over ``transfer_sockets_per_source``
        connections instead of serializing head-of-line on one).  Stripe
        assignment is the CALLER's — sticky per in-flight chunk — and a
        stripe keeps its connection (and its lane) for the pool's
        lifetime, so per-connection FIFO ordering still holds within a
        stripe."""
        if stripe <= 0:
            return self.get(address)
        key = f"{address}\x00stripe{stripe}"
        c = self._clients.get(key)
        if c is None or c._closed:
            c = RpcClient(address, lane=self._lane_for(key), bulk=True)
            if self._push_handler is not None:
                c.on_push(self._push_handler)
            self._clients[key] = c
        return c

    async def close(self, address: str):
        """Drop one connection — including its transfer stripes; their
        pending futures fail with ConnectionLost (used to force-surface a
        peer the caller KNOWS is dead without waiting on EOF delivery)."""
        c = self._clients.pop(address, None)
        if c is not None:
            await c.close()
        prefix = f"{address}\x00stripe"
        for key in [k for k in self._clients if k.startswith(prefix)]:
            sc = self._clients.pop(key, None)
            if sc is not None:
                await sc.close()

    async def close_all(self):
        for c in self._clients.values():
            await c.close()
        self._clients.clear()
