"""Object storage: per-process memory store + per-node shared-memory (plasma-equivalent) store.

Two tiers, like the reference:

* **MemoryStore** — in-process store for small objects and for location records of large
  ones (reference: ``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``).
  Values <= ``max_direct_call_object_size`` live here in full and travel inline in RPC
  replies; larger objects are represented by a :class:`PlasmaRecord` pointing at the node
  that holds the primary copy.

* **NodeObjectStore** — per-node shared-memory store (reference: plasma,
  ``src/ray/object_manager/plasma/store.h:55``).  Implemented as mmap'd files under
  ``/dev/shm`` (one per object — the same mmap+fd design plasma uses, minus the custom
  dlmalloc arena; an arena allocator is a planned C++ upgrade).  Any process on the node
  attaches segments by path for zero-copy reads.  Create/seal/get/free run inside the node
  agent; eviction is LRU over sealed, unpinned objects with optional spill-to-disk
  (reference: ``src/ray/raylet/local_object_manager.h:41``).
"""

from __future__ import annotations

import asyncio
import mmap
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from .config import get_config
from .ids import ObjectID

_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()

class ObjectStoreFullError(Exception):
    pass


class ChunkNotAvailable(Exception):
    """``read_chunk`` hit a range an in-progress (partial) holder has not
    landed yet — the puller should re-stripe the chunk onto another source
    and re-probe this one's advertised ranges, NOT treat the holder as
    dead.  Travels across the RPC boundary as a RemoteError cause."""


# -- sealed-range bookkeeping (partial-object serving) ----------------------

def range_add(ranges: list, start: int, end: int) -> list:
    """Fold [start, end) into a sorted, merged list of [start, end) pairs."""
    out = []
    placed = False
    for s, e in ranges:
        if e < start or s > end:
            if not placed and s > end:
                out.append([start, end])
                placed = True
            out.append([s, e])
        else:
            start, end = min(s, start), max(e, end)
    if not placed:
        out.append([start, end])
    out.sort()
    return out


def range_covers(ranges: list, start: int, end: int) -> bool:
    """True iff [start, end) lies inside one merged range."""
    for s, e in ranges:
        if s <= start and end <= e:
            return True
    return False


# ---------------------------------------------------------------------------
# Shared-memory segments
# ---------------------------------------------------------------------------

class _PoolAttachCache:
    """Per-process cache of mmaps of whole pool files.  A pool slice path is
    ``{pool_path}#{offset}``; every attacher maps the pool once and indexes
    by offset (the plasma client pattern: one fd per store, not per object)."""

    def __init__(self):
        self._maps: Dict[str, mmap.mmap] = {}

    def view(self, pool_path: str, offset: int, size: int,
             populate_write: bool = False) -> memoryview:
        mm = self._maps.get(pool_path)
        if mm is None:
            fd = os.open(pool_path, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, os.path.getsize(pool_path))
            finally:
                os.close(fd)
            self._maps[pool_path] = mm
        if populate_write and size >= (1 << 20) and \
                hasattr(mmap, "MADV_POPULATE_WRITE"):
            # Writers: establish writable PTEs for the slice in one syscall
            # instead of ~size/4K minor faults during the memcpy (pages are
            # already resident from the store's startup prefault).
            page = mmap.PAGESIZE
            start = (offset // page) * page
            length = offset + size - start
            try:
                mm.madvise(mmap.MADV_POPULATE_WRITE, start, length)
            except (OSError, ValueError):
                pass
        return memoryview(mm)[offset:offset + size]


_pool_attach = _PoolAttachCache()


class ShmSegment:
    """One mmap'd file; create-mode unlinks on free, attach-mode is read-only.

    Attach-mode also understands pool-slice paths (``pool#offset``), mapping
    the whole pool once per process via ``_pool_attach``."""

    def __init__(self, path: str, size: int, create: bool):
        self.path = path
        self.size = size
        self.created = create
        self.mm = None
        self._slice: Optional[memoryview] = None
        if "#" in path and not create:
            pool_path, off = path.rsplit("#", 1)
            # Attach-for-write is the writer's path (puts / task returns):
            # pre-populate the slice's PTEs so the copy runs at memcpy speed.
            self._slice = _pool_attach.view(pool_path, int(off), size,
                                            populate_write=True)
            return
        flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)

    def view(self) -> memoryview:
        if self._slice is not None:
            return self._slice
        return memoryview(self.mm)

    def close(self):
        if self.mm is None:
            return  # pool slice: the attach cache owns the pool mapping
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass  # outstanding zero-copy views keep the map alive until GC

    def unlink(self):
        if self.mm is None:
            return  # pool slice: only the owner's allocator frees the range
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


class PoolSlice:
    """Owner-side segment living inside the node's arena: close() is a no-op
    (the pool owns the mapping); unlink() returns the range to the
    allocator."""

    __slots__ = ("pool", "offset", "size")

    def __init__(self, pool, offset: int, size: int):
        self.pool = pool
        self.offset = offset
        self.size = size

    @property
    def path(self) -> str:
        return f"{self.pool.path}#{self.offset}"

    def view(self) -> memoryview:
        return self.pool.view(self.offset, self.size)

    def close(self):
        pass

    def unlink(self):
        self.pool.free(self.offset)


def shm_path_for(store_name: str, object_id: ObjectID) -> str:
    return os.path.join(_SHM_DIR, f"raytpu-{store_name}-{object_id.hex()}")


# ---------------------------------------------------------------------------
# Node-level store (runs inside the node agent)
# ---------------------------------------------------------------------------

@dataclass
class _Entry:
    segment: ShmSegment
    size: int
    sealed: bool = False
    pinned: int = 0          # pin count: live reader views + peer transfers
    freed: bool = False      # owner freed it while pins were live (deferred)
    last_access: float = field(default_factory=time.monotonic)
    #: sealed [start, end) byte ranges of an UNSEALED entry being pulled —
    #: the chunk ledger publishes each landed chunk here so ``read_chunk``
    #: can serve it to later pullers before the whole object seals
    #: (partial-object serving; None once sealed / for plain writers).
    avail: Optional[list] = None


@dataclass
class _ProxyEntry:
    """Zero-copy reference to a SAME-HOST peer store's sealed object.

    Plasma's same-node sharing, extended across node agents that share one
    /dev/shm: instead of copying the bytes through a socket, this node serves
    the SOURCE store's pool-slice path directly (workers attach it with the
    same ``_pool_attach`` mmap cache) and the source holds a pin for us until
    we free.  An N-node same-host broadcast therefore moves zero bytes —
    every consumer reads the origin's pages through the shared page cache."""
    path: str
    size: int
    source_addr: str
    pinned: int = 0          # reader pins on the proxy itself
    freed: bool = False      # free deferred until the pins release


class NodeObjectStore:
    """Plasma-equivalent store; all methods are called on the agent's IO loop."""

    def __init__(self, name: str, capacity: int = 0):
        cfg = get_config()
        if capacity <= 0:
            capacity = cfg.object_store_memory
        if capacity <= 0:
            try:
                total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
            except (ValueError, OSError):
                total = 8 << 30
            capacity = int(total * 0.3)
        self.name = name
        self.capacity = capacity
        self.used = 0
        self._entries: Dict[ObjectID, _Entry] = {}
        # Same-host zero-copy references (see _ProxyEntry): not counted
        # against capacity — the bytes live in the source node's arena.
        self._proxies: Dict[ObjectID, _ProxyEntry] = {}
        # attach-mode cache for serving chunks of paths this store does
        # not own (proxy relaying; see _attach_view)
        self._attach_maps: Dict[str, ShmSegment] = {}
        self._sealed_events: Dict[ObjectID, asyncio.Event] = {}
        self.num_creates = 0
        self.num_evictions = 0
        # Spill-on-evict is ON by default (reference: raylet spills rather
        # than drop; local_object_manager.h:41) — an empty config value means
        # "pick a default dir", not "disable".  Set it to "off" to disable.
        if cfg.object_spilling_dir == "off":
            self.spill_dir = None
        else:
            self.spill_dir = cfg.object_spilling_dir or os.path.join(
                tempfile.gettempdir(), "raytpu", "spill")
        # Native arena (C++ first-fit allocator over ONE shm mapping — the
        # plasma design): per-object create cost drops from
        # open+ftruncate+mmap+page-zero to an allocator call.  Falls back to
        # file-per-object when the native lib can't build.
        self.pool = None
        if cfg.object_store_use_native_pool:
            try:
                from ray_tpu.native import ShmPool
                # The path doubles as the attach-cache key in every client
                # process, so it must be unique per store INSTANCE: a reused
                # path would hand cached stale mmaps of a dead session's
                # arena to long-lived clients.
                uniq = os.urandom(4).hex()
                self.pool = ShmPool(
                    os.path.join(_SHM_DIR, f"raytpu-pool-{name}-{uniq}"),
                    capacity)
            except Exception:
                self.pool = None
        # Arena prefault is LAZY: triggered by the first create(), so a
        # cluster that never touches plasma doesn't eagerly commit gigabytes
        # of tmpfs RAM (see _maybe_start_prefault).
        self._prefault_started = not (
            self.pool is not None and cfg.object_store_prefault
            and hasattr(mmap, "MADV_POPULATE_WRITE"))

    def _maybe_start_prefault(self):
        """Fault the arena's tmpfs pages in once, on first use (plasma
        pre-touches its arena the same way): steady-state creates then cost
        an allocator call, and writers copy into already-resident pages at
        memcpy speed instead of page-fault speed.  Runs in a background
        thread, CHUNKED: madvise holds the GIL for the syscall's duration,
        so one whole-arena call would freeze the agent loop (capacity
        defaults to 30% of RAM).  The low region is prefaulted first —
        first-fit allocation reuses it most."""
        if self._prefault_started:
            return
        self._prefault_started = True
        import threading

        def _prefault(path=self.pool.path,
                      nbytes=min(self.capacity, 8 << 30)):
            try:
                fd = os.open(path, os.O_RDWR)
                try:
                    mm = mmap.mmap(fd, nbytes)
                finally:
                    os.close(fd)
                step = 128 << 20
                for off in range(0, nbytes, step):
                    mm.madvise(mmap.MADV_POPULATE_WRITE, off,
                               min(step, nbytes - off))
                    time.sleep(0)  # yield the GIL between chunks
                mm.close()
            except Exception:
                pass

        threading.Thread(target=_prefault, name="store-prefault",
                         daemon=True).start()

    # -- creation ---------------------------------------------------------

    def create(self, object_id: ObjectID, size: int) -> str:
        """Allocate a segment; returns the shm path the writer should mmap."""
        self._maybe_start_prefault()
        if object_id in self._entries:
            return self._entries[object_id].segment.path
        if size > self.capacity:
            raise ObjectStoreFullError(
                f"object {object_id} ({size} B) exceeds store capacity {self.capacity} B")
        if self.used + size > self.capacity:
            self._evict(self.used + size - self.capacity)
        if self.pool is not None:
            seg = self._pool_alloc(size)
        else:
            path = shm_path_for(self.name, object_id)
            try:
                seg = ShmSegment(path, size, create=True)
            except FileExistsError:
                os.unlink(path)
                seg = ShmSegment(path, size, create=True)
        self._entries[object_id] = _Entry(segment=seg, size=size)
        self.used += size
        self.num_creates += 1
        return seg.path

    def _pool_alloc(self, size: int) -> "PoolSlice":
        off = self.pool.alloc(size)
        if off < 0:
            # allocator full (fragmentation can strand capacity even when
            # self.used says otherwise): evict until the arena yields
            self._evict(max(size, 1))
            off = self.pool.alloc(size)
            if off < 0:
                self._evict(self.capacity // 4)
                off = self.pool.alloc(size)
        if off < 0:
            raise ObjectStoreFullError(
                f"store {self.name}: arena cannot place {size} B "
                f"(used={self.pool.used}/{self.pool.capacity})")
        return PoolSlice(self.pool, off, size)

    def create_and_write(self, object_id: ObjectID, data) -> str:
        path = self.create(object_id, len(data))
        e = self._entries[object_id]
        e.segment.view()[: len(data)] = data
        self.seal(object_id)
        return path

    def seal(self, object_id: ObjectID):
        e = self._entries[object_id]
        e.sealed = True
        e.avail = None  # full: range map no longer meaningful
        ev = self._sealed_events.pop(object_id, None)
        if ev:
            ev.set()

    def mark_available(self, object_id: ObjectID, offset: int, length: int):
        """Publish one landed chunk of an in-progress pull: ``read_chunk``
        serves it and ``object_info`` advertises it from now on."""
        e = self._entries.get(object_id)
        if e is None or e.sealed or e.freed:
            return
        e.avail = range_add(e.avail or [], offset, offset + length)

    def available_ranges(self, object_id: ObjectID) -> Optional[list]:
        """Sealed ranges of an UNSEALED entry (None when nothing landed or
        the object is sealed/freed/absent)."""
        e = self._entries.get(object_id)
        if e is None or e.sealed or e.freed:
            return None
        return e.avail

    # -- reads ------------------------------------------------------------

    def contains(self, object_id: ObjectID) -> bool:
        """Locally retrievable: sealed in shm, proxied from a same-host peer,
        OR spilled to this node's disk (get_path restores spilled entries
        transparently — without this, fetch_object would declare a
        spilled-but-local object lost)."""
        # freed-deferred records (owner freed them; only live reader pins
        # keep the bytes around) are NOT retrievable: serving them would
        # hand new fetchers a deleted object whose slice is reclaimed the
        # moment the last pin releases.
        e = self._entries.get(object_id)
        if e is not None and e.sealed and not e.freed:
            return True
        p = self._proxies.get(object_id)
        if p is not None and not p.freed:
            return True
        return object_id in self._spilled

    async def wait_sealed(self, object_id: ObjectID, timeout: float | None = None) -> bool:
        e = self._entries.get(object_id)
        if e is not None and e.sealed:
            return True
        ev = self._sealed_events.setdefault(object_id, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def add_proxy(self, object_id: ObjectID, path: str, size: int,
                  source_addr: str):
        self._proxies[object_id] = _ProxyEntry(path, size, source_addr)

    def get_path(self, object_id: ObjectID) -> Optional[tuple[str, int]]:
        p = self._proxies.get(object_id)
        if p is not None and not p.freed:
            return p.path, p.size
        e = self._entries.get(object_id)
        if e is not None and e.freed:
            return None  # freed-deferred: not servable (see contains())
        if e is None or not e.sealed:
            if e is None:
                self._maybe_restore(object_id)
                e = self._entries.get(object_id)
                if e is None or not e.sealed:
                    return None
            else:
                return None
        e.last_access = time.monotonic()
        return e.segment.path, e.size

    def read_chunk(self, object_id: ObjectID, offset: int, length: int) -> bytes:
        e = self._entries.get(object_id)
        if e is None:
            # Same-host proxy holders ARE byte sources: serve straight off
            # the source pool slice / file the proxy references (remote
            # pullers that can't zero-copy attach still get the bytes).
            p = self._proxies.get(object_id)
            if p is not None and not p.freed:
                return bytes(self._attach_view(p.path, p.size)
                             [offset:offset + length])
            self._maybe_restore(object_id)
            e = self._entries[object_id]
        if e.freed:
            # deleted, just not yet reclaimed (reader pins live): a remote
            # puller must try another source, not copy a freed object
            raise KeyError(f"object {object_id} is freed")
        if not e.sealed:
            # partial holder (an in-progress pull publishing its ledger):
            # serve only ranges that actually landed — anything else is a
            # typed miss the puller re-stripes, never silent garbage
            if not (e.avail and range_covers(e.avail, offset,
                                             offset + length)):
                raise ChunkNotAvailable(
                    f"object {object_id}: [{offset}, {offset + length}) "
                    f"not yet held (have {e.avail or []})")
        e.last_access = time.monotonic()
        return bytes(e.segment.view()[offset:offset + length])

    def _attach_view(self, path: str, size: int) -> memoryview:
        """Attach-mode view over a path this store does not own (proxy
        serving); file-backed attaches are cached like ShmReader's."""
        if "#" in path:
            pool_path, off = path.rsplit("#", 1)
            return _pool_attach.view(pool_path, int(off), size)
        seg = self._attach_maps.get(path)
        if seg is None:
            seg = ShmSegment(path, size, create=False)
            self._attach_maps[path] = seg
        return seg.view()[:size]

    def size_of(self, object_id: ObjectID) -> Optional[int]:
        e = self._entries.get(object_id)
        return e.size if e else None

    # -- lifetime ---------------------------------------------------------
    #
    # Pin/release protocol (the plasma-client contract the round-1 reader
    # deferred with a defensive copy per read): a consumer pins BEFORE
    # taking a zero-copy view over an arena slice, and releases when its
    # last view is garbage-collected.  While any pin is live the slice's
    # offset cannot be recycled: eviction skips pinned entries, and an
    # owner-initiated free is DEFERRED — marked ``freed`` and completed by
    # the final unpin.  All transitions run on the agent's IO loop, so
    # pin-after-locate cannot race an eviction.

    def pin(self, object_id: ObjectID):
        e = self._entries.get(object_id)
        if e:
            e.pinned += 1

    def pin_for_read(self, object_id: ObjectID) -> Optional[str]:
        """Pin a same-host proxy OR a sealed entry for a reader's view.

        Returns the KIND of record pinned ("proxy" / "local", truthy) or
        None.  Priority mirrors :meth:`get_path` — the record pinned must
        be the one whose path the reader was handed, or the pin protects
        the wrong mapping.  The caller keeps the kind and passes it back
        to :meth:`unpin` so a release can never decrement the twin record
        (entry and proxy can coexist with independent pin counts)."""
        p = self._proxies.get(object_id)
        if p is not None and not p.freed:
            p.pinned += 1
            return "proxy"
        e = self._entries.get(object_id)
        if e is not None and e.sealed and not e.freed:
            e.pinned += 1
            return "local"
        return None

    def unpin(self, object_id: ObjectID, kind: Optional[str] = None) -> Optional[str]:
        """Drop one pin; completes a deferred free when the last pin goes.
        Returns the proxy SOURCE address if the completed free was a proxy
        (the caller owes the source an unpin notify).

        ``kind`` ("local" / "proxy", from :meth:`pin_for_read`) targets the
        record the pin was granted on.  Without it (transfer pins via
        :meth:`pin`, legacy callers) the release lands on whichever record
        actually holds pins — never on a zero-pin twin, which would leak
        the real pin and prematurely release another reader's."""
        e = self._entries.get(object_id)
        p = self._proxies.get(object_id)
        te = e if kind != "proxy" else None
        tp = p if kind != "local" else None
        if te is not None and (te.pinned > 0 or tp is None or tp.pinned == 0):
            if te.pinned > 0:
                te.pinned -= 1
        elif tp is not None and tp.pinned > 0:
            tp.pinned -= 1
        # A deferred free completes only once NO pins remain on EITHER
        # record — free() defers when either is pinned, so completion must
        # mirror that or a proxy reader's slice is reclaimed under it.
        freed = (e is not None and e.freed) or (p is not None and p.freed)
        live = ((e.pinned if e is not None else 0)
                + (p.pinned if p is not None else 0))
        if freed and live == 0:
            return self._complete_free(object_id)
        return None

    def free(self, object_id: ObjectID, force: bool = False) -> Optional[str]:
        """Free a local object.  Returns the SOURCE agent address when the
        freed entry was a same-host proxy — the caller must send the unpin.

        A free that lands while reader pins are live is deferred (the
        segment must not be unlinked — or its arena offset recycled — under
        a live zero-copy view); the last unpin completes it.  ``force``
        (shutdown) skips the deferral."""
        e = self._entries.get(object_id)
        p = self._proxies.get(object_id)
        if not force and ((e is not None and e.pinned > 0)
                          or (p is not None and p.pinned > 0)):
            if e is not None:
                e.freed = True
            if p is not None:
                p.freed = True
            # The spilled copy has no readers — reclaim it now.
            spilled = self._spilled.pop(object_id, None)
            if spilled:
                try:
                    os.unlink(spilled)
                except OSError:
                    pass
            return None
        return self._complete_free(object_id)

    def _complete_free(self, object_id: ObjectID) -> Optional[str]:
        proxy = self._proxies.pop(object_id, None)
        if proxy is not None:
            # drop the chunk-serving attach mapping (if any): holding it
            # past the proxy's life would keep the origin's unlinked shm
            # pages resident forever on a long-lived agent
            seg = self._attach_maps.pop(proxy.path, None)
            if seg is not None:
                seg.close()
        # A freed object may live in shm, on the spill disk, or both.
        spilled = self._spilled.pop(object_id, None)
        if spilled:
            try:
                os.unlink(spilled)
            except OSError:
                pass
        e = self._entries.pop(object_id, None)
        # Freeing an UNSEALED entry (a failed striped pull) must wake any
        # wait_sealed() waiter NOW: they re-resolve (get_path -> None ->
        # remote pull) instead of sleeping out their full timeout against
        # an event nothing will ever set.
        ev = self._sealed_events.pop(object_id, None)
        if ev:
            ev.set()
        if e is None:
            return proxy.source_addr if proxy else None
        self.used -= e.size
        e.segment.close()
        e.segment.unlink()
        return proxy.source_addr if proxy else None

    def _evict(self, need_bytes: int):
        """LRU-evict sealed unpinned entries; spill them first if configured."""
        # A freed-deferred entry (only its proxy twin is pinned) must not be
        # spilled/evicted as if live: it would gain a spill copy nothing
        # cleans up and _maybe_restore could resurrect a freed object.
        victims = sorted(
            (e for oid, e in self._entries.items()
             if e.sealed and e.pinned == 0 and not e.freed),
            key=lambda e: e.last_access)
        freed = 0
        for e in victims:
            if freed >= need_bytes:
                break
            oid = next(k for k, v in self._entries.items() if v is e)
            if self.spill_dir:
                self._spill(oid, e)
            self._entries.pop(oid)
            self.used -= e.size
            freed += e.size
            e.segment.close()
            e.segment.unlink()
            self.num_evictions += 1
        if freed < need_bytes:
            raise ObjectStoreFullError(
                f"store {self.name}: need {need_bytes} B but only {freed} B evictable "
                f"(used={self.used}/{self.capacity})")

    def _spill(self, object_id: ObjectID, e: _Entry):
        os.makedirs(self.spill_dir, exist_ok=True)
        path = os.path.join(self.spill_dir, f"{self.name}-{object_id.hex()}.spill")
        with open(path, "wb") as f:
            f.write(e.segment.view())
        self._spilled.setdefault(object_id, path)

    @property
    def _spilled(self) -> Dict[ObjectID, str]:
        if not hasattr(self, "_spilled_map"):
            self._spilled_map: Dict[ObjectID, str] = {}
        return self._spilled_map

    def _maybe_restore(self, object_id: ObjectID):
        path = self._spilled.pop(object_id, None)
        if path is None:
            return
        with open(path, "rb") as f:
            data = f.read()
        self.create_and_write(object_id, data)
        os.unlink(path)

    def stats(self) -> dict:
        largest_free = 0
        if self.pool is not None:
            try:
                largest_free = self.pool.largest_free
            except Exception:
                pass
        return {
            "capacity": self.capacity,
            "used": self.used,
            "largest_free_block": largest_free,
            "num_objects": len(self._entries),
            "num_proxies": len(self._proxies),
            "num_creates": self.num_creates,
            "num_evictions": self.num_evictions,
            "num_pinned": sum(1 for e in self._entries.values()
                              if e.pinned > 0)
            + sum(1 for p in self._proxies.values() if p.pinned > 0),
            "num_deferred_frees": sum(1 for e in self._entries.values()
                                      if e.freed)
            + sum(1 for p in self._proxies.values() if p.freed),
        }

    def objects(self) -> list:
        """Per-object report rows (the ``raytpu memory`` data source)."""
        rows = []
        for oid, e in self._entries.items():
            rows.append({"object_id": oid.hex(), "size": e.size,
                         "sealed": e.sealed, "pinned": e.pinned,
                         "freed": e.freed, "kind": "local",
                         "path": e.segment.path})
        for oid, p in self._proxies.items():
            rows.append({"object_id": oid.hex(), "size": p.size,
                         "sealed": True, "pinned": p.pinned,
                         "freed": p.freed, "kind": "proxy",
                         "path": p.path, "source": p.source_addr})
        for oid, path in self._spilled.items():
            rows.append({"object_id": oid.hex(), "size": None,
                         "sealed": True, "pinned": 0, "freed": False,
                         "kind": "spilled", "path": path})
        return rows

    def shutdown(self):
        for seg in self._attach_maps.values():
            seg.close()
        self._attach_maps.clear()
        for oid in list(self._entries):
            self.free(oid, force=True)
        # spill files of still-referenced-but-evicted objects would otherwise
        # outlive the session and accumulate under the shared default dir
        for oid in list(self._spilled):
            path = self._spilled.pop(oid)
            try:
                os.unlink(path)
            except OSError:
                pass
        if self.pool is not None:
            self.pool.close(unlink=True)
            self.pool = None


# ---------------------------------------------------------------------------
# Per-process attach-side client
# ---------------------------------------------------------------------------

class ShmReader:
    """Attach-side reads of store segments.

    File-per-object segments are cached and returned zero-copy (an unlinked
    file stays valid for existing mmaps, so eviction cannot invalidate a
    reader's view).  Pool slices have two read modes:

    * :meth:`view` — ZERO-COPY readonly view, valid only while the caller
      holds a store pin on the object (the pin/release protocol: the agent
      pinned the entry at fetch time, and the pin blocks eviction and
      defers frees until the consumer's views die).
    * :meth:`read` — the unpinned fallback: copy out and let the caller
      re-validate with ``store_verify`` (the arena recycles offsets, so an
      unpinned view is not a stable identity).  Records a ``get_copy``
      event so the copy-discipline tests can pin the pinned path at zero.
    """

    def __init__(self):
        self._maps: Dict[str, ShmSegment] = {}

    def _stats(self):
        from .serialization import _stats  # the one lazy cycle-break shim
        return _stats()

    def view(self, path: str, size: int) -> memoryview:
        """Zero-copy view; caller must hold a pin for pool slices.

        Returned WRITABLE (ctypes ``from_buffer`` in the lease-attach step
        needs it); the deserializer wraps every slice readonly before any
        user code can touch it."""
        if "#" in path:
            pool_path, off = path.rsplit("#", 1)
            mv = _pool_attach.view(pool_path, int(off), size)
        else:
            seg = self._maps.get(path)
            if seg is None:
                seg = ShmSegment(path, size, create=False)
                self._maps[path] = seg
            mv = seg.view()[:size]
        self._stats().record("get_zero_copy", size)
        return mv

    def read(self, path: str, size: int):
        if "#" in path:
            pool_path, off = path.rsplit("#", 1)
            self._stats().record("get_copy", size)
            return bytes(_pool_attach.view(pool_path, int(off), size))
        seg = self._maps.get(path)
        if seg is None:
            seg = ShmSegment(path, size, create=False)
            self._maps[path] = seg
        return seg.view()[:size]

    def drop(self, path: str):
        seg = self._maps.pop(path, None)
        if seg:
            seg.close()

    def close(self):
        for seg in self._maps.values():
            seg.close()
        self._maps.clear()


# ---------------------------------------------------------------------------
# In-process memory store (owner-side)
# ---------------------------------------------------------------------------

@dataclass
class PlasmaRecord:
    """Location record for a large object (primary copy + replicas)."""
    size: int
    locations: list  # list of (node_id_hex, agent_address)


@dataclass
class ErrorRecord:
    """A task error stored in place of a value; raised on get.

    ``system`` marks faults recorded by the RUNTIME (OOM kill, worker crash,
    actor death) rather than raised by the task body: system faults surface
    typed from ``get`` (ray.exceptions semantics), while user exceptions —
    even RayTpuError subclasses a task let propagate from an inner get —
    wrap in TaskError so failures stay attributed to the right task."""
    error: bytes  # pickled exception
    system: bool = False


class MemoryStore:
    """Owner-side store: object id -> inline bytes | PlasmaRecord | ErrorRecord.

    Readiness is an asyncio.Event per pending id, so `get`/`wait` can await
    completion of the producing task (reference: GetRequest futures in
    memory_store.cc).
    """

    def __init__(self):
        self._values: Dict[ObjectID, object] = {}
        self._events: Dict[ObjectID, asyncio.Event] = {}

    def put(self, object_id: ObjectID, record) -> None:
        self._values[object_id] = record
        ev = self._events.pop(object_id, None)
        if ev:
            ev.set()

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._values

    def get_if_exists(self, object_id: ObjectID):
        return self._values.get(object_id)

    async def wait_ready(self, object_id: ObjectID, timeout: float | None = None) -> bool:
        if object_id in self._values:
            return True
        ev = self._events.setdefault(object_id, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def free(self, object_id: ObjectID):
        self._values.pop(object_id, None)
        self._events.pop(object_id, None)

    def __len__(self):
        return len(self._values)
