"""Object storage: per-process memory store + per-node shared-memory (plasma-equivalent) store.

Two tiers, like the reference:

* **MemoryStore** — in-process store for small objects and for location records of large
  ones (reference: ``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``).
  Values <= ``max_direct_call_object_size`` live here in full and travel inline in RPC
  replies; larger objects are represented by a :class:`PlasmaRecord` pointing at the node
  that holds the primary copy.

* **NodeObjectStore** — per-node shared-memory store (reference: plasma,
  ``src/ray/object_manager/plasma/store.h:55``).  Implemented as mmap'd files under
  ``/dev/shm`` (one per object — the same mmap+fd design plasma uses, minus the custom
  dlmalloc arena; an arena allocator is a planned C++ upgrade).  Any process on the node
  attaches segments by path for zero-copy reads.  Create/seal/get/free run inside the node
  agent; eviction is LRU over sealed, unpinned objects with optional spill-to-disk
  (reference: ``src/ray/raylet/local_object_manager.h:41``).
"""

from __future__ import annotations

import asyncio
import json
import mmap
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from . import external_spill, object_explain
from .config import get_config
from .external_spill import (KEY_TIER_EXTERNAL, KEY_TIER_LOCAL,
                             spill_metrics)
from .ids import ObjectID
from .object_explain import KEY_RESTORE, KEY_SPILL, ObjectEvent

_SHM_DIR = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()

class ObjectStoreFullError(Exception):
    pass


class ChunkNotAvailable(Exception):
    """``read_chunk`` hit a range an in-progress (partial) holder has not
    landed yet — the puller should re-stripe the chunk onto another source
    and re-probe this one's advertised ranges, NOT treat the holder as
    dead.  Travels across the RPC boundary as a RemoteError cause."""


# -- sealed-range bookkeeping (partial-object serving) ----------------------

def range_add(ranges: list, start: int, end: int) -> list:
    """Fold [start, end) into a sorted, merged list of [start, end) pairs."""
    out = []
    placed = False
    for s, e in ranges:
        if e < start or s > end:
            if not placed and s > end:
                out.append([start, end])
                placed = True
            out.append([s, e])
        else:
            start, end = min(s, start), max(e, end)
    if not placed:
        out.append([start, end])
    out.sort()
    return out


def range_covers(ranges: list, start: int, end: int) -> bool:
    """True iff [start, end) lies inside one merged range."""
    for s, e in ranges:
        if s <= start and end <= e:
            return True
    return False


# ---------------------------------------------------------------------------
# Shared-memory segments
# ---------------------------------------------------------------------------

class _PoolAttachCache:
    """Per-process cache of mmaps of whole pool files.  A pool slice path is
    ``{pool_path}#{offset}``; every attacher maps the pool once and indexes
    by offset (the plasma client pattern: one fd per store, not per object)."""

    def __init__(self):
        self._maps: Dict[str, mmap.mmap] = {}

    def view(self, pool_path: str, offset: int, size: int,
             populate_write: bool = False) -> memoryview:
        mm = self._maps.get(pool_path)
        if mm is None:
            fd = os.open(pool_path, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, os.path.getsize(pool_path))
            finally:
                os.close(fd)
            self._maps[pool_path] = mm
        if populate_write and size >= (1 << 20) and \
                hasattr(mmap, "MADV_POPULATE_WRITE"):
            # Writers: establish writable PTEs for the slice in one syscall
            # instead of ~size/4K minor faults during the memcpy (pages are
            # already resident from the store's startup prefault).
            page = mmap.PAGESIZE
            start = (offset // page) * page
            length = offset + size - start
            try:
                mm.madvise(mmap.MADV_POPULATE_WRITE, start, length)
            except (OSError, ValueError):
                pass
        return memoryview(mm)[offset:offset + size]


_pool_attach = _PoolAttachCache()


class ShmSegment:
    """One mmap'd file; create-mode unlinks on free, attach-mode is read-only.

    Attach-mode also understands pool-slice paths (``pool#offset``), mapping
    the whole pool once per process via ``_pool_attach``."""

    def __init__(self, path: str, size: int, create: bool):
        self.path = path
        self.size = size
        self.created = create
        self.mm = None
        self._slice: Optional[memoryview] = None
        if "#" in path and not create:
            pool_path, off = path.rsplit("#", 1)
            # Attach-for-write is the writer's path (puts / task returns):
            # pre-populate the slice's PTEs so the copy runs at memcpy speed.
            self._slice = _pool_attach.view(pool_path, int(off), size,
                                            populate_write=True)
            return
        flags = os.O_RDWR | (os.O_CREAT | os.O_EXCL if create else 0)
        fd = os.open(path, flags, 0o600)
        try:
            if create:
                os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)

    def view(self) -> memoryview:
        if self._slice is not None:
            return self._slice
        return memoryview(self.mm)

    def close(self):
        if self.mm is None:
            return  # pool slice: the attach cache owns the pool mapping
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass  # outstanding zero-copy views keep the map alive until GC

    def unlink(self):
        if self.mm is None:
            return  # pool slice: only the owner's allocator frees the range
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


class PoolSlice:
    """Owner-side segment living inside the node's arena: close() is a no-op
    (the pool owns the mapping); unlink() returns the range to the
    allocator."""

    __slots__ = ("pool", "offset", "size")

    def __init__(self, pool, offset: int, size: int):
        self.pool = pool
        self.offset = offset
        self.size = size

    @property
    def path(self) -> str:
        return f"{self.pool.path}#{self.offset}"

    def view(self) -> memoryview:
        return self.pool.view(self.offset, self.size)

    def close(self):
        pass

    def unlink(self):
        self.pool.free(self.offset)


def shm_path_for(store_name: str, object_id: ObjectID) -> str:
    return os.path.join(_SHM_DIR, f"raytpu-{store_name}-{object_id.hex()}")


# ---------------------------------------------------------------------------
# Node-level store (runs inside the node agent)
# ---------------------------------------------------------------------------

@dataclass
class _Entry:
    segment: ShmSegment
    size: int
    #: bytes actually ALLOCATED in the arena (>= size: a reserve-then-
    #: write put may seal-truncate ``size`` to the exact encoding, but
    #: the allocator range — and this store's ``used`` accounting —
    #: stays the reservation until free)
    alloc: int = 0
    sealed: bool = False
    pinned: int = 0          # pin count: live reader views + peer transfers
    freed: bool = False      # owner freed it while pins were live (deferred)
    last_access: float = field(default_factory=time.monotonic)
    #: the owning CoreWorker's address (piggybacked on store_create): lets
    #: the spill/drain paths register an external copy back with the owner
    #: as a non-node location (None for legacy/ownerless writes)
    owner: Optional[str] = None
    #: sealed [start, end) byte ranges of an UNSEALED entry being pulled —
    #: the chunk ledger publishes each landed chunk here so ``read_chunk``
    #: can serve it to later pullers before the whole object seals
    #: (partial-object serving; None once sealed / for plain writers).
    avail: Optional[list] = None


@dataclass
class _ProxyEntry:
    """Zero-copy reference to a SAME-HOST peer store's sealed object.

    Plasma's same-node sharing, extended across node agents that share one
    /dev/shm: instead of copying the bytes through a socket, this node serves
    the SOURCE store's pool-slice path directly (workers attach it with the
    same ``_pool_attach`` mmap cache) and the source holds a pin for us until
    we free.  An N-node same-host broadcast therefore moves zero bytes —
    every consumer reads the origin's pages through the shared page cache."""
    path: str
    size: int
    source_addr: str
    pinned: int = 0          # reader pins on the proxy itself
    freed: bool = False      # free deferred until the pins release


class NodeObjectStore:
    """Plasma-equivalent store; all methods are called on the agent's IO loop."""

    def __init__(self, name: str, capacity: int = 0):
        cfg = get_config()
        if capacity <= 0:
            capacity = cfg.object_store_memory
        if capacity <= 0:
            try:
                total = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
            except (ValueError, OSError):
                total = 8 << 30
            capacity = int(total * 0.3)
        self.name = name
        self.capacity = capacity
        self.used = 0
        self._entries: Dict[ObjectID, _Entry] = {}
        # Same-host zero-copy references (see _ProxyEntry): not counted
        # against capacity — the bytes live in the source node's arena.
        self._proxies: Dict[ObjectID, _ProxyEntry] = {}
        # attach-mode cache for serving chunks of paths this store does
        # not own (proxy relaying; see _attach_view)
        self._attach_maps: Dict[str, ShmSegment] = {}
        self._sealed_events: Dict[ObjectID, asyncio.Event] = {}
        self.num_creates = 0
        self.num_evictions = 0
        # Spill-on-evict is ON by default (reference: raylet spills rather
        # than drop; local_object_manager.h:41) — an empty config value means
        # "pick a default dir", not "disable".  Set it to "off" to disable.
        # Files live under a PER-STORE subdirectory with a pid marker, so a
        # restarted node incarnation's orphan sweep (sweep_orphan_spill_dirs)
        # can delete a dead store's leftovers without touching live peers'.
        if cfg.object_spilling_dir == "off":
            self.spill_root = None
            self.spill_dir = None
        else:
            self.spill_root = cfg.object_spilling_dir or os.path.join(
                tempfile.gettempdir(), "raytpu", "spill")
            self.spill_dir = os.path.join(self.spill_root, self.name)
        # External durability tier (core/external_spill.py): spilled objects
        # go to a cluster-readable fsspec URI instead of node-local disk and
        # are registered with the owner as a non-node location — they
        # survive this node's preemption and restore through ANY node's
        # pull path.
        self.external_uri = cfg.object_spilling_external_uri or None
        #: oid -> external URI (recorded at spill-submit time; the write
        #: itself may still be in flight — see _ext_writes)
        self._spilled_external: Dict[ObjectID, str] = {}
        #: oid -> in-flight external write future: readers racing the
        #: write wait it out; frees racing it mark _ext_drop_after_write
        self._ext_writes: Dict[ObjectID, "object"] = {}
        self._ext_drop_after_write: set = set()
        #: oid -> monotonic deadline of a restore-failure backoff window:
        #: after the agent's off-loop restore fails, the SYNC fallback in
        #: _maybe_restore must not re-attempt the same network read on the
        #: event loop — the pull path covers instead
        self._ext_backoff: Dict[ObjectID, float] = {}
        self._ext_pool = None
        #: agent hook, called (object_id, uri, owner) off-loop once an
        #: external spill write LANDS — registers the URI with the owner
        self.on_external_spill = None
        #: flight-recorder hook, called (object_id, event, detail) on the
        #: store's lifecycle transitions (SEALED/SPILLED/RESTORED/FREED/
        #: FREE_DEFERRED) — the agent buffers these and flushes them to
        #: the GCS object-event ring.  Only fired when the object plane's
        #: kill switch is on; None outside an agent.
        self.on_object_event = None
        #: spill-tier size ledgers: byte sizes of this store's local-disk
        #: and external-tier copies (the entry record dies with the evict,
        #: so the tier totals `memory_summary` reports need their own
        #: bookkeeping).
        self._spilled_sizes: Dict[ObjectID, int] = {}
        self._ext_sizes: Dict[ObjectID, int] = {}
        # Native arena (C++ first-fit allocator over ONE shm mapping — the
        # plasma design): per-object create cost drops from
        # open+ftruncate+mmap+page-zero to an allocator call.  Falls back to
        # file-per-object when the native lib can't build.
        self.pool = None
        if cfg.object_store_use_native_pool:
            try:
                from ray_tpu.native import ShmPool
                # The path doubles as the attach-cache key in every client
                # process, so it must be unique per store INSTANCE: a reused
                # path would hand cached stale mmaps of a dead session's
                # arena to long-lived clients.
                uniq = os.urandom(4).hex()
                self.pool = ShmPool(
                    os.path.join(_SHM_DIR, f"raytpu-pool-{name}-{uniq}"),
                    capacity)
            except Exception:
                self.pool = None
        # Arena prefault is LAZY: triggered by the first create(), so a
        # cluster that never touches plasma doesn't eagerly commit gigabytes
        # of tmpfs RAM (see _maybe_start_prefault).
        self._prefault_started = not (
            self.pool is not None and cfg.object_store_prefault
            and hasattr(mmap, "MADV_POPULATE_WRITE"))

    def _maybe_start_prefault(self):
        """Fault the arena's tmpfs pages in once, on first use (plasma
        pre-touches its arena the same way): steady-state creates then cost
        an allocator call, and writers copy into already-resident pages at
        memcpy speed instead of page-fault speed.  Runs in a background
        thread, CHUNKED: madvise holds the GIL for the syscall's duration,
        so one whole-arena call would freeze the agent loop (capacity
        defaults to 30% of RAM).  The low region is prefaulted first —
        first-fit allocation reuses it most."""
        if self._prefault_started:
            return
        self._prefault_started = True
        import threading

        def _prefault(path=self.pool.path,
                      nbytes=min(self.capacity, 8 << 30)):
            try:
                fd = os.open(path, os.O_RDWR)
                try:
                    mm = mmap.mmap(fd, nbytes)
                finally:
                    os.close(fd)
                step = 128 << 20
                for off in range(0, nbytes, step):
                    mm.madvise(mmap.MADV_POPULATE_WRITE, off,
                               min(step, nbytes - off))
                    time.sleep(0)  # yield the GIL between chunks
                mm.close()
            except Exception:
                pass

        threading.Thread(target=_prefault, name="store-prefault",
                         daemon=True).start()

    def _event(self, object_id: ObjectID, event: str, **detail):
        """Stamp one lifecycle transition onto the flight recorder (via
        the agent's buffer).  One boolean check when the plane is off."""
        cb = self.on_object_event
        if cb is None or not object_explain.enabled():
            return
        try:
            cb(object_id, event, detail)
        except Exception:
            pass

    # -- creation ---------------------------------------------------------

    def create(self, object_id: ObjectID, size: int,
               owner: Optional[str] = None) -> str:
        """Allocate a segment; returns the shm path the writer should mmap.

        ``owner`` (the owning CoreWorker's address, when the caller knows
        it) is remembered on the entry so a later spill/drain can register
        an external copy back with the owner."""
        self._maybe_start_prefault()
        if object_id in self._entries:
            e = self._entries[object_id]
            if owner and not e.owner:
                e.owner = owner
            return e.segment.path
        if size > self.capacity:
            raise ObjectStoreFullError(
                f"object {object_id} ({size} B) exceeds store capacity {self.capacity} B")
        if self.used + size > self.capacity:
            self._evict(self.used + size - self.capacity)
        if self.pool is not None:
            seg = self._pool_alloc(size)
        else:
            path = shm_path_for(self.name, object_id)
            try:
                seg = ShmSegment(path, size, create=True)
            except FileExistsError:
                os.unlink(path)
                seg = ShmSegment(path, size, create=True)
        self._entries[object_id] = _Entry(segment=seg, size=size,
                                          alloc=size, owner=owner)
        self.used += size
        self.num_creates += 1
        return seg.path

    def _pool_alloc(self, size: int) -> "PoolSlice":
        off = self.pool.alloc(size)
        if off < 0:
            # allocator full (fragmentation can strand capacity even when
            # self.used says otherwise): evict until the arena yields
            self._evict(max(size, 1))
            off = self.pool.alloc(size)
            if off < 0:
                self._evict(self.capacity // 4)
                off = self.pool.alloc(size)
        if off < 0:
            raise ObjectStoreFullError(
                f"store {self.name}: arena cannot place {size} B "
                f"(used={self.pool.used}/{self.pool.capacity})")
        return PoolSlice(self.pool, off, size)

    def create_and_write(self, object_id: ObjectID, data,
                         owner: Optional[str] = None) -> str:
        path = self.create(object_id, len(data), owner=owner)
        e = self._entries[object_id]
        e.segment.view()[: len(data)] = data
        self.seal(object_id)
        return path

    def seal(self, object_id: ObjectID, truncate_to: Optional[int] = None):
        """Seal; ``truncate_to`` shrinks the entry's DATA size to the
        exact bytes written (reserve-then-write puts reserve an upper
        bound): readers, transfers and spills then never touch the
        ``[used, reserved)`` tail — which is recycled arena memory, i.e.
        another object's stale bytes.  The allocator range (and ``used``
        accounting) stays the reservation until free."""
        e = self._entries[object_id]
        e.sealed = True
        if truncate_to is not None and 0 < truncate_to < e.size:
            e.size = truncate_to
        e.avail = None  # full: range map no longer meaningful
        ev = self._sealed_events.pop(object_id, None)
        if ev:
            ev.set()
        self._event(object_id, ObjectEvent.SEALED, size=e.size)

    def mark_available(self, object_id: ObjectID, offset: int, length: int):
        """Publish one landed chunk of an in-progress pull: ``read_chunk``
        serves it and ``object_info`` advertises it from now on."""
        e = self._entries.get(object_id)
        if e is None or e.sealed or e.freed:
            return
        e.avail = range_add(e.avail or [], offset, offset + length)

    def available_ranges(self, object_id: ObjectID) -> Optional[list]:
        """Sealed ranges of an UNSEALED entry (None when nothing landed or
        the object is sealed/freed/absent)."""
        e = self._entries.get(object_id)
        if e is None or e.sealed or e.freed:
            return None
        return e.avail

    # -- reads ------------------------------------------------------------

    def contains(self, object_id: ObjectID) -> bool:
        """Locally retrievable: sealed in shm, proxied from a same-host peer,
        OR spilled to this node's disk (get_path restores spilled entries
        transparently — without this, fetch_object would declare a
        spilled-but-local object lost)."""
        # freed-deferred records (owner freed them; only live reader pins
        # keep the bytes around) are NOT retrievable: serving them would
        # hand new fetchers a deleted object whose slice is reclaimed the
        # moment the last pin releases.
        e = self._entries.get(object_id)
        if e is not None and e.sealed and not e.freed:
            return True
        p = self._proxies.get(object_id)
        if p is not None and not p.freed:
            return True
        return (object_id in self._spilled
                or object_id in self._spilled_external)

    async def wait_sealed(self, object_id: ObjectID, timeout: float | None = None) -> bool:
        e = self._entries.get(object_id)
        if e is not None and e.sealed:
            return True
        ev = self._sealed_events.setdefault(object_id, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def add_proxy(self, object_id: ObjectID, path: str, size: int,
                  source_addr: str):
        self._proxies[object_id] = _ProxyEntry(path, size, source_addr)

    def get_path(self, object_id: ObjectID) -> Optional[tuple[str, int]]:
        p = self._proxies.get(object_id)
        if p is not None and not p.freed:
            return p.path, p.size
        e = self._entries.get(object_id)
        if e is not None and e.freed:
            return None  # freed-deferred: not servable (see contains())
        if e is None or not e.sealed:
            if e is None:
                self._maybe_restore(object_id)
                e = self._entries.get(object_id)
                if e is None or not e.sealed:
                    return None
            else:
                return None
        e.last_access = time.monotonic()
        return e.segment.path, e.size

    def read_chunk_view(self, object_id: ObjectID, offset: int,
                        length: int) -> memoryview:
        """ZERO-COPY chunk serving: a view straight over the shm mapping
        (sealed entry, same-host proxy slice, or a covered range of an
        in-progress pull).  The caller must consume the view WITHIN the
        current event-loop tick — the vectored reply path flushes
        synchronously and the asyncio transport copies any unsent
        remainder into its own buffer before returning, and eviction/free
        run on this same loop, so no recycle can interleave with a
        same-tick consumer.  Holding the view across an ``await`` would
        break that invariant."""
        e = self._entries.get(object_id)
        if e is None:
            # Same-host proxy holders ARE byte sources: serve straight off
            # the source pool slice / file the proxy references (remote
            # pullers that can't zero-copy attach still get the bytes).
            p = self._proxies.get(object_id)
            if p is not None and not p.freed:
                return self._attach_view(p.path, p.size)[
                    offset:offset + length]
            self._maybe_restore(object_id)
            e = self._entries[object_id]
        if e.freed:
            # deleted, just not yet reclaimed (reader pins live): a remote
            # puller must try another source, not copy a freed object
            raise KeyError(f"object {object_id} is freed")
        if not e.sealed:
            # partial holder (an in-progress pull publishing its ledger):
            # serve only ranges that actually landed — anything else is a
            # typed miss the puller re-stripes, never silent garbage
            if not (e.avail and range_covers(e.avail, offset,
                                             offset + length)):
                raise ChunkNotAvailable(
                    f"object {object_id}: [{offset}, {offset + length}) "
                    f"not yet held (have {e.avail or []})")
        e.last_access = time.monotonic()
        return e.segment.view()[offset:offset + length]

    def read_chunk(self, object_id: ObjectID, offset: int, length: int) -> bytes:
        """Copying chunk read (non-RPC consumers; the serving hot path is
        :meth:`read_chunk_view`)."""
        view = self.read_chunk_view(object_id, offset, length)
        return view.tobytes()

    def _attach_view(self, path: str, size: int) -> memoryview:
        """Attach-mode view over a path this store does not own (proxy
        serving); file-backed attaches are cached like ShmReader's."""
        if "#" in path:
            pool_path, off = path.rsplit("#", 1)
            return _pool_attach.view(pool_path, int(off), size)
        seg = self._attach_maps.get(path)
        if seg is None:
            seg = ShmSegment(path, size, create=False)
            self._attach_maps[path] = seg
        return seg.view()[:size]

    def size_of(self, object_id: ObjectID) -> Optional[int]:
        e = self._entries.get(object_id)
        return e.size if e else None

    # -- lifetime ---------------------------------------------------------
    #
    # Pin/release protocol (the plasma-client contract the round-1 reader
    # deferred with a defensive copy per read): a consumer pins BEFORE
    # taking a zero-copy view over an arena slice, and releases when its
    # last view is garbage-collected.  While any pin is live the slice's
    # offset cannot be recycled: eviction skips pinned entries, and an
    # owner-initiated free is DEFERRED — marked ``freed`` and completed by
    # the final unpin.  All transitions run on the agent's IO loop, so
    # pin-after-locate cannot race an eviction.

    def pin(self, object_id: ObjectID):
        e = self._entries.get(object_id)
        if e:
            e.pinned += 1

    def pin_for_read(self, object_id: ObjectID) -> Optional[str]:
        """Pin a same-host proxy OR a sealed entry for a reader's view.

        Returns the KIND of record pinned ("proxy" / "local", truthy) or
        None.  Priority mirrors :meth:`get_path` — the record pinned must
        be the one whose path the reader was handed, or the pin protects
        the wrong mapping.  The caller keeps the kind and passes it back
        to :meth:`unpin` so a release can never decrement the twin record
        (entry and proxy can coexist with independent pin counts)."""
        p = self._proxies.get(object_id)
        if p is not None and not p.freed:
            p.pinned += 1
            return "proxy"
        e = self._entries.get(object_id)
        if e is not None and e.sealed and not e.freed:
            e.pinned += 1
            return "local"
        return None

    def pin_for_serve(self, object_id: ObjectID) -> Optional[str]:
        """Pin the record :meth:`read_chunk_view` just served a view of —
        the bulk-transfer server's bracket: its serving THREADS push the
        view into the kernel outside the store's loop, so the view must
        be pin-protected for the send's duration (unlike the same-tick
        RPC reply path).  Mirrors read_chunk_view's service order (entry
        first, proxy only when no entry) and, unlike
        :meth:`pin_for_read`, also pins UNSEALED partial entries (their
        landed ranges are servable).  Returns the kind for
        :meth:`unpin`."""
        e = self._entries.get(object_id)
        if e is not None and not e.freed:
            e.pinned += 1
            return "local"
        p = self._proxies.get(object_id)
        if p is not None and not p.freed:
            p.pinned += 1
            return "proxy"
        return None

    def unpin(self, object_id: ObjectID, kind: Optional[str] = None) -> Optional[str]:
        """Drop one pin; completes a deferred free when the last pin goes.
        Returns the proxy SOURCE address if the completed free was a proxy
        (the caller owes the source an unpin notify).

        ``kind`` ("local" / "proxy", from :meth:`pin_for_read`) targets the
        record the pin was granted on.  Without it (transfer pins via
        :meth:`pin`, legacy callers) the release lands on whichever record
        actually holds pins — never on a zero-pin twin, which would leak
        the real pin and prematurely release another reader's."""
        e = self._entries.get(object_id)
        p = self._proxies.get(object_id)
        te = e if kind != "proxy" else None
        tp = p if kind != "local" else None
        if te is not None and (te.pinned > 0 or tp is None or tp.pinned == 0):
            if te.pinned > 0:
                te.pinned -= 1
        elif tp is not None and tp.pinned > 0:
            tp.pinned -= 1
        # A deferred free completes only once NO pins remain on EITHER
        # record — free() defers when either is pinned, so completion must
        # mirror that or a proxy reader's slice is reclaimed under it.
        freed = (e is not None and e.freed) or (p is not None and p.freed)
        live = ((e.pinned if e is not None else 0)
                + (p.pinned if p is not None else 0))
        if freed and live == 0:
            return self._complete_free(object_id)
        return None

    def free(self, object_id: ObjectID, force: bool = False) -> Optional[str]:
        """Free a local object.  Returns the SOURCE agent address when the
        freed entry was a same-host proxy — the caller must send the unpin.

        A free that lands while reader pins are live is deferred (the
        segment must not be unlinked — or its arena offset recycled — under
        a live zero-copy view); the last unpin completes it.  ``force``
        (shutdown) skips the deferral."""
        e = self._entries.get(object_id)
        p = self._proxies.get(object_id)
        if not force and ((e is not None and e.pinned > 0)
                          or (p is not None and p.pinned > 0)):
            if e is not None:
                e.freed = True
            if p is not None:
                p.freed = True
            self._event(object_id, ObjectEvent.FREE_DEFERRED,
                        pins=(e.pinned if e is not None else 0)
                        + (p.pinned if p is not None else 0))
            # The spilled copy has no readers — reclaim it now.
            spilled = self._spilled.pop(object_id, None)
            self._spilled_sizes.pop(object_id, None)
            if spilled:
                try:
                    os.unlink(spilled)
                except OSError:
                    pass
            self._drop_external(object_id)
            return None
        return self._complete_free(object_id, drop_external=not force)

    def _drop_external(self, object_id: ObjectID):
        """Delete this store's external-tier copy of a freed object.  If the
        write is still in flight, deletion chains behind its completion
        (free-during-spill race: the copy must not survive the free)."""
        uri = self._spilled_external.pop(object_id, None)
        self._ext_sizes.pop(object_id, None)
        if uri is None:
            return
        if object_id in self._ext_writes:
            self._ext_drop_after_write.add(object_id)
        else:
            try:
                # off the caller's (event-loop) thread: a gs:// delete is
                # a network round trip, and free() runs in RPC handlers
                self._ext_executor().submit(external_spill.delete, uri)
            except Exception:
                pass

    def _complete_free(self, object_id: ObjectID,
                       drop_external: bool = True) -> Optional[str]:
        proxy = self._proxies.pop(object_id, None)
        if proxy is not None:
            # drop the chunk-serving attach mapping (if any): holding it
            # past the proxy's life would keep the origin's unlinked shm
            # pages resident forever on a long-lived agent
            seg = self._attach_maps.pop(proxy.path, None)
            if seg is not None:
                seg.close()
        # A freed object may live in shm, on the spill disk, the external
        # tier, or several at once.
        spilled = self._spilled.pop(object_id, None)
        self._spilled_owners.pop(object_id, None)
        self._spilled_sizes.pop(object_id, None)
        if spilled:
            try:
                os.unlink(spilled)
            except OSError:
                pass
        had_external = object_id in self._spilled_external
        if drop_external:
            self._drop_external(object_id)
        e = self._entries.pop(object_id, None)
        # Freeing an UNSEALED entry (a failed striped pull) must wake any
        # wait_sealed() waiter NOW: they re-resolve (get_path -> None ->
        # remote pull) instead of sleeping out their full timeout against
        # an event nothing will ever set.
        ev = self._sealed_events.pop(object_id, None)
        if ev:
            ev.set()
        if e is not None or proxy is not None or spilled is not None \
                or had_external:
            # stamp only when this store actually held SOMETHING: the
            # owner's free fans out to every listed location, including
            # nodes whose copy is already gone
            self._event(object_id, ObjectEvent.FREED)
        if e is None:
            return proxy.source_addr if proxy else None
        self.used -= e.alloc or e.size
        e.segment.close()
        e.segment.unlink()
        return proxy.source_addr if proxy else None

    def _evict(self, need_bytes: int):
        """LRU-evict sealed unpinned entries; spill them first if configured."""
        # A freed-deferred entry (only its proxy twin is pinned) must not be
        # spilled/evicted as if live: it would gain a spill copy nothing
        # cleans up and _maybe_restore could resurrect a freed object.
        victims = sorted(
            (e for oid, e in self._entries.items()
             if e.sealed and e.pinned == 0 and not e.freed),
            key=lambda e: e.last_access)
        freed = 0
        for e in victims:
            if freed >= need_bytes:
                break
            oid = next(k for k, v in self._entries.items() if v is e)
            if self.spill_dir or self.external_uri:
                self._spill(oid, e)
            self._entries.pop(oid)
            self.used -= e.alloc or e.size
            freed += e.alloc or e.size
            e.segment.close()
            e.segment.unlink()
            self.num_evictions += 1
        if freed < need_bytes:
            raise ObjectStoreFullError(
                f"store {self.name}: need {need_bytes} B but only {freed} B evictable "
                f"(used={self.used}/{self.capacity})")

    def _spill(self, object_id: ObjectID, e: _Entry):
        """Spill one evicted entry: to the external fsspec tier when
        configured (durable — survives this node), else to node-local disk.

        The external write runs on a background thread against a
        synchronous COPY of the bytes (the segment is reclaimed the moment
        eviction returns); the URI is recorded immediately so readers that
        race the write wait on the in-flight future instead of missing the
        copy.  Once the write lands, ``on_external_spill`` tells the agent
        to register the URI with the owner as a non-node location."""
        if self.external_uri:
            self._spill_external(object_id, e)
            return
        os.makedirs(self.spill_dir, exist_ok=True)
        self._write_spill_marker()
        path = os.path.join(self.spill_dir, f"{self.name}-{object_id.hex()}.spill")
        with open(path, "wb") as f:
            # [:e.size]: a seal-truncated entry's segment is the (larger)
            # reservation — the tail is recycled arena bytes, never data
            f.write(e.segment.view()[:e.size])
        self._spilled.setdefault(object_id, path)
        self._spilled_sizes[object_id] = e.size
        if e.owner:
            # the entry record dies with the evict; the drain path still
            # needs to know whom to tell when it re-homes this file
            self._spilled_owners[object_id] = e.owner
        m = spill_metrics()
        if m is not None:
            m["bytes"].inc_key(KEY_TIER_LOCAL, e.size)
        object_explain.ledger_record(KEY_SPILL, e.size)
        self._event(object_id, ObjectEvent.SPILLED, tier="local",
                    size=e.size)

    def _spill_external(self, object_id: ObjectID, e: _Entry):
        if (object_id in self._spilled_external
                and object_id not in self._ext_writes):
            # restore->evict cycle: the landed external copy (kept by
            # _maybe_restore precisely for this) is still valid — byte
            # content is immutable once sealed, so re-uploading the whole
            # object (and re-firing the owner registration) is pure waste
            return
        data = bytes(e.segment.view()[:e.size])
        uri = external_spill.object_uri(self.external_uri, object_id)
        self._spilled_external[object_id] = uri
        self._ext_sizes[object_id] = len(data)
        object_explain.ledger_record(KEY_SPILL, len(data))
        self._event(object_id, ObjectEvent.SPILLED, tier="external",
                    size=len(data), uri=uri)
        fut = self._ext_executor().submit(external_spill.write, uri, data)
        self._ext_writes[object_id] = fut
        fut.add_done_callback(
            lambda f, oid=object_id, uri=uri, owner=e.owner, data=data:
            self._ext_write_done(oid, uri, owner, f, data))

    def _ext_executor(self):
        if self._ext_pool is None:
            import concurrent.futures
            self._ext_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=2, thread_name_prefix="ext-spill")
        return self._ext_pool

    def _ext_write_done(self, object_id: ObjectID, uri: str,
                        owner: Optional[str], fut, data=None):
        """Runs on the spill writer thread (dict single-op mutations only —
        GIL-atomic; everything loop-bound goes through on_external_spill,
        which the agent marshals back onto its loop)."""
        self._ext_writes.pop(object_id, None)
        try:
            n = fut.result()
        except Exception:
            # failed write: the recorded URI is a dangling promise — drop
            # it so contains()/restore stop advertising a copy that isn't,
            # and FALL BACK to the local spill disk (the entry is already
            # evicted; without this the sole copy is simply gone while the
            # owner still routes pullers here)
            self._spilled_external.pop(object_id, None)
            self._ext_sizes.pop(object_id, None)
            if object_id in self._ext_drop_after_write:
                self._ext_drop_after_write.discard(object_id)
                return  # freed mid-write: nothing to preserve
            if data is not None and self.spill_dir:
                try:
                    os.makedirs(self.spill_dir, exist_ok=True)
                    self._write_spill_marker()
                    path = os.path.join(
                        self.spill_dir,
                        f"{self.name}-{object_id.hex()}.spill")
                    with open(path, "wb") as f:
                        f.write(data)
                    self._spilled[object_id] = path
                    self._spilled_sizes[object_id] = len(data)
                    self._event(object_id, ObjectEvent.SPILLED,
                                tier="local", size=len(data),
                                fallback=True)
                    if owner:
                        self._spilled_owners[object_id] = owner
                    m = spill_metrics()
                    if m is not None:
                        m["bytes"].inc_key(KEY_TIER_LOCAL, len(data))
                except Exception:
                    pass
            return
        m = spill_metrics()
        if m is not None:
            m["bytes"].inc_key(KEY_TIER_EXTERNAL, n)
        if object_id in self._ext_drop_after_write:
            # freed while the write was in flight: the copy must not
            # outlive the free
            self._ext_drop_after_write.discard(object_id)
            try:
                external_spill.delete(uri)
            except Exception:
                pass
            return
        cb = self.on_external_spill
        if cb is not None and self._spilled_external.get(object_id) == uri:
            try:
                cb(object_id, uri, owner)
            except Exception:
                pass

    def _write_spill_marker(self):
        """Pid marker for the orphan sweep: a later incarnation on this
        host deletes spill dirs whose writing process is gone."""
        marker = os.path.join(self.spill_dir, "owner.json")
        if not os.path.exists(marker):
            try:
                with open(marker, "w") as f:
                    json.dump({"pid": os.getpid(),
                               "store": self.name,
                               "started_at": time.time()}, f)
            except OSError:
                pass

    @property
    def _spilled(self) -> Dict[ObjectID, str]:
        if not hasattr(self, "_spilled_map"):
            self._spilled_map: Dict[ObjectID, str] = {}
        return self._spilled_map

    @property
    def _spilled_owners(self) -> Dict[ObjectID, str]:
        """Owner address per LOCALLY spilled object (the entry that held it
        is gone; the drain path re-homes these files and must register the
        new location with the owner)."""
        if not hasattr(self, "_spilled_owners_map"):
            self._spilled_owners_map: Dict[ObjectID, str] = {}
        return self._spilled_owners_map

    def external_only(self, object_id: ObjectID) -> bool:
        """True when the ONLY local knowledge of this object is an
        external-tier URI — the restore is a (possibly remote) network
        read the agent must run off-loop, unlike the local-disk path."""
        e = self._entries.get(object_id)
        if e is not None and e.sealed and not e.freed:
            return False
        p = self._proxies.get(object_id)
        if p is not None and not p.freed:
            return False
        return (object_id not in self._spilled
                and object_id in self._spilled_external)

    def restore_external_bytes(self, object_id: ObjectID,
                               data: bytes) -> None:
        """Land externally-restored bytes back into the store (the agent
        read them off-loop; this runs ON the loop).  The external record is
        kept — other nodes may be routed at it and re-evicting reuses it."""
        if object_id in self._entries:
            return
        self.create_and_write(object_id, data)
        object_explain.ledger_record(KEY_RESTORE, len(data))
        self._event(object_id, ObjectEvent.RESTORED, tier="external",
                    size=len(data))

    def _maybe_restore(self, object_id: ObjectID):
        path = self._spilled.pop(object_id, None)
        if path is not None:
            self._spilled_sizes.pop(object_id, None)
            t0 = time.monotonic()
            with open(path, "rb") as f:
                data = f.read()
            self.create_and_write(object_id, data,
                                  owner=self._spilled_owners.pop(
                                      object_id, None))
            os.unlink(path)
            m = spill_metrics()
            if m is not None:
                m["restore_seconds"].observe(time.monotonic() - t0)
            object_explain.ledger_record(KEY_RESTORE, len(data))
            self._event(object_id, ObjectEvent.RESTORED, tier="local",
                        size=len(data))
            return
        # External tier: wait out an in-flight spill write (the reader
        # raced the evict), then read the URI back into the store.  The
        # external copy is NOT deleted — it may be registered with the
        # owner as a location other nodes are pulling from; the owner's
        # free is its single deletion point.
        #
        # This SYNCHRONOUS branch is the local-disk-style fallback for
        # direct store users; the agent's read paths go through the
        # off-loop ``_restore_external`` FIRST and only land here after it
        # failed, so the in-flight wait is capped short rather than
        # letting one slow tier freeze the caller for a minute.
        uri = self._spilled_external.get(object_id)
        if uri is None:
            return
        if time.monotonic() < self._ext_backoff.get(object_id, 0.0):
            return  # off-loop restore just failed: don't retry ON-loop
        fut = self._ext_writes.get(object_id)
        if fut is not None:
            try:
                fut.result(timeout=5.0)
            except Exception:
                return  # write failed/slow; the caller's pull path covers
        try:
            data = external_spill.timed_read(uri)
        except Exception:
            self._ext_backoff[object_id] = time.monotonic() + 5.0
            return
        self._ext_backoff.pop(object_id, None)
        self.create_and_write(object_id, data)
        object_explain.ledger_record(KEY_RESTORE, len(data))
        self._event(object_id, ObjectEvent.RESTORED, tier="external",
                    size=len(data))

    def arena_report(self) -> dict:
        """Arena introspection: free bytes, largest free block, the
        fragmentation fraction (1 - largest_free/free: 0 = one contiguous
        free region, ->1 = free space shredded into slivers), and a
        coarse free-block size histogram when the native pool exposes
        block enumeration."""
        free = max(0, self.capacity - self.used)
        largest_free = free if self.pool is None else 0
        hist = None
        if self.pool is not None:
            try:
                largest_free = self.pool.largest_free
            except Exception:
                largest_free = 0
            blocks = []
            try:
                blocks = self.pool.free_blocks()
            except Exception:
                blocks = []
            if blocks:
                # power-of-4 buckets from 64 KiB: bounded (8 buckets),
                # readable, and enough to see sliver accumulation
                bounds = [64 << 10, 256 << 10, 1 << 20, 4 << 20, 16 << 20,
                          64 << 20, 256 << 20]
                hist = [0] * (len(bounds) + 1)
                for b in blocks:
                    i = 0
                    while i < len(bounds) and b > bounds[i]:
                        i += 1
                    hist[i] += 1
                hist = {"bounds": bounds, "counts": hist,
                        "num_free_blocks": len(blocks)}
        frag = 0.0
        if free > 0 and largest_free > 0:
            frag = max(0.0, 1.0 - largest_free / free)
        elif free > 0 and self.pool is not None:
            frag = 1.0  # free capacity exists but no allocatable block
        return {"free": free, "largest_free_block": largest_free,
                "frag_fraction": round(frag, 4), "free_block_hist": hist}

    def stats(self) -> dict:
        arena = self.arena_report()
        return {
            "capacity": self.capacity,
            "used": self.used,
            "largest_free_block": arena["largest_free_block"],
            "frag_fraction": arena["frag_fraction"],
            "free_block_hist": arena["free_block_hist"],
            "num_objects": len(self._entries),
            "num_proxies": len(self._proxies),
            "num_creates": self.num_creates,
            "num_evictions": self.num_evictions,
            "num_pinned": sum(1 for e in self._entries.values()
                              if e.pinned > 0)
            + sum(1 for p in self._proxies.values() if p.pinned > 0),
            "num_deferred_frees": sum(1 for e in self._entries.values()
                                      if e.freed)
            + sum(1 for p in self._proxies.values() if p.freed),
            "num_spilled_local": len(self._spilled),
            "num_spilled_external": len(self._spilled_external),
            # spill-tier byte totals (the external tier was invisible to
            # memory_summary before — only the spill counter saw it)
            "spilled_local_bytes": sum(self._spilled_sizes.get(oid, 0)
                                       for oid in self._spilled),
            "spilled_external_bytes": sum(self._ext_sizes.values()),
        }

    def objects(self) -> list:
        """Per-object report rows (the ``raytpu memory`` data source)."""
        rows = []
        for oid, e in self._entries.items():
            rows.append({"object_id": oid.hex(), "size": e.size,
                         "sealed": e.sealed, "pinned": e.pinned,
                         "freed": e.freed, "kind": "local",
                         "path": e.segment.path})
        for oid, p in self._proxies.items():
            rows.append({"object_id": oid.hex(), "size": p.size,
                         "sealed": True, "pinned": p.pinned,
                         "freed": p.freed, "kind": "proxy",
                         "path": p.path, "source": p.source_addr})
        for oid, path in self._spilled.items():
            rows.append({"object_id": oid.hex(),
                         "size": self._spilled_sizes.get(oid),
                         "sealed": True, "pinned": 0, "freed": False,
                         "kind": "spilled", "path": path})
        for oid, uri in self._spilled_external.items():
            if oid in self._entries:
                continue  # restored: already reported as "local"
            rows.append({"object_id": oid.hex(),
                         "size": self._ext_sizes.get(oid),
                         "sealed": True, "pinned": 0, "freed": False,
                         "kind": "external", "path": uri})
        return rows

    def shutdown(self):
        for seg in self._attach_maps.values():
            seg.close()
        self._attach_maps.clear()
        for oid in list(self._entries):
            self.free(oid, force=True)
        # spill files of still-referenced-but-evicted objects would otherwise
        # outlive the session and accumulate under the shared default dir.
        # External-tier copies are deliberately NOT deleted here: they may be
        # registered with owners as live locations (the whole point of the
        # durability tier); the owner's free — or a later orphan GC — is
        # their deletion point.
        for oid in list(self._spilled):
            path = self._spilled.pop(oid)
            try:
                os.unlink(path)
            except OSError:
                pass
        if self.spill_dir and os.path.isdir(self.spill_dir):
            # this incarnation's (now empty) spill subdir + marker
            import shutil
            shutil.rmtree(self.spill_dir, ignore_errors=True)
        if self._ext_pool is not None:
            self._ext_pool.shutdown(wait=False)
            self._ext_pool = None
        if self.pool is not None:
            self.pool.close(unlink=True)
            self.pool = None


def sweep_orphan_spill_dirs(spill_root: str, grace_s: float = 60.0) -> int:
    """Delete per-store local spill directories whose writing process is
    gone (a restarted node incarnation cleaning up its previous life).
    Each store writes an ``owner.json`` pid marker on first spill; a dir
    whose pid is dead — or that has spill files but no marker — is an
    orphan.  Marker-less dirs younger than ``grace_s`` are SKIPPED: a
    sibling agent's first spill creates the dir a moment before its
    marker write lands, and sweeping that window would delete a live
    store's file out from under its evict.  Returns the number of
    directories removed."""
    import shutil
    removed = 0
    try:
        names = os.listdir(spill_root)
    except OSError:
        return 0
    for name in names:
        d = os.path.join(spill_root, name)
        if not os.path.isdir(d):
            continue
        marker = os.path.join(d, "owner.json")
        pid = None
        try:
            with open(marker) as f:
                pid = int(json.load(f).get("pid", 0))
        except (OSError, ValueError, TypeError):
            pid = None
        if pid is None:
            try:
                if time.time() - os.path.getmtime(d) < grace_s:
                    continue  # mid-creation by a live sibling
            except OSError:
                continue
        alive = False
        if pid:
            try:
                os.kill(pid, 0)
                alive = True
            except ProcessLookupError:
                alive = False
            except PermissionError:
                alive = True  # exists, owned by someone else
            except OSError:
                alive = False
        if not alive:
            shutil.rmtree(d, ignore_errors=True)
            removed += 1
    return removed


# ---------------------------------------------------------------------------
# Per-process attach-side client
# ---------------------------------------------------------------------------

class ShmReader:
    """Attach-side reads of store segments.

    File-per-object segments are cached and returned zero-copy (an unlinked
    file stays valid for existing mmaps, so eviction cannot invalidate a
    reader's view).  Pool slices have two read modes:

    * :meth:`view` — ZERO-COPY readonly view, valid only while the caller
      holds a store pin on the object (the pin/release protocol: the agent
      pinned the entry at fetch time, and the pin blocks eviction and
      defers frees until the consumer's views die).
    * :meth:`read` — the unpinned fallback: copy out and let the caller
      re-validate with ``store_verify`` (the arena recycles offsets, so an
      unpinned view is not a stable identity).  Records a ``get_copy``
      event so the copy-discipline tests can pin the pinned path at zero.
    """

    def __init__(self):
        self._maps: Dict[str, ShmSegment] = {}

    def _stats(self):
        from .serialization import _stats  # the one lazy cycle-break shim
        return _stats()

    def view(self, path: str, size: int) -> memoryview:
        """Zero-copy view; caller must hold a pin for pool slices.

        Returned WRITABLE (ctypes ``from_buffer`` in the lease-attach step
        needs it); the deserializer wraps every slice readonly before any
        user code can touch it."""
        if "#" in path:
            pool_path, off = path.rsplit("#", 1)
            mv = _pool_attach.view(pool_path, int(off), size)
        else:
            seg = self._maps.get(path)
            if seg is None:
                seg = ShmSegment(path, size, create=False)
                self._maps[path] = seg
            mv = seg.view()[:size]
        self._stats().record("get_zero_copy", size)
        return mv

    def read(self, path: str, size: int):
        if "#" in path:
            pool_path, off = path.rsplit("#", 1)
            self._stats().record("get_copy", size)
            return bytes(_pool_attach.view(pool_path, int(off), size))
        seg = self._maps.get(path)
        if seg is None:
            seg = ShmSegment(path, size, create=False)
            self._maps[path] = seg
        return seg.view()[:size]

    def drop(self, path: str):
        seg = self._maps.pop(path, None)
        if seg:
            seg.close()

    def close(self):
        for seg in self._maps.values():
            seg.close()
        self._maps.clear()


# ---------------------------------------------------------------------------
# In-process memory store (owner-side)
# ---------------------------------------------------------------------------

@dataclass
class PlasmaRecord:
    """Location record for a large object (primary copy + replicas)."""
    size: int
    locations: list  # list of (node_id_hex, agent_address)


@dataclass
class ErrorRecord:
    """A task error stored in place of a value; raised on get.

    ``system`` marks faults recorded by the RUNTIME (OOM kill, worker crash,
    actor death) rather than raised by the task body: system faults surface
    typed from ``get`` (ray.exceptions semantics), while user exceptions —
    even RayTpuError subclasses a task let propagate from an inner get —
    wrap in TaskError so failures stay attributed to the right task."""
    error: bytes  # pickled exception
    system: bool = False


class MemoryStore:
    """Owner-side store: object id -> inline bytes | PlasmaRecord | ErrorRecord.

    Readiness is an asyncio.Event per pending id, so `get`/`wait` can await
    completion of the producing task (reference: GetRequest futures in
    memory_store.cc).
    """

    def __init__(self):
        self._values: Dict[ObjectID, object] = {}
        self._events: Dict[ObjectID, asyncio.Event] = {}
        # Batch waiters (wait_many): object id -> [waiter, ...] where a
        # waiter is a [remaining_count, future] pair shared by every id of
        # one batched get.  put() decrements O(1); the future resolves
        # when the LAST id lands — one future + one wakeup per batch
        # instead of one Event + one wait_for coroutine per ref (the
        # owner-loop cost that capped big drains; ROADMAP 5).
        self._batch_waiters: Dict[ObjectID, list] = {}

    def put(self, object_id: ObjectID, record) -> None:
        self._values[object_id] = record
        ev = self._events.pop(object_id, None)
        if ev:
            ev.set()
        waiters = self._batch_waiters.pop(object_id, None)
        if waiters:
            for w in waiters:
                w[0] -= 1
                if w[0] <= 0 and not w[1].done():
                    w[1].set_result(True)

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._values

    def get_if_exists(self, object_id: ObjectID):
        return self._values.get(object_id)

    async def wait_ready(self, object_id: ObjectID, timeout: float | None = None) -> bool:
        if object_id in self._values:
            return True
        ev = self._events.setdefault(object_id, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def wait_many(self, object_ids, timeout: float | None = None) -> bool:
        """Await ALL of ``object_ids`` being present — one shared future
        for the whole batch (see _batch_waiters).  Timed-out waiters are
        left registered but done; put() skips them, and the entry list is
        popped whenever the id eventually lands (bounded by in-flight
        batches, not history)."""
        missing = [oid for oid in object_ids if oid not in self._values]
        if not missing:
            return True
        fut = asyncio.get_event_loop().create_future()
        waiter = [len(missing), fut]
        for oid in missing:
            self._batch_waiters.setdefault(oid, []).append(waiter)
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def free(self, object_id: ObjectID):
        self._values.pop(object_id, None)
        self._events.pop(object_id, None)

    def __len__(self):
        return len(self._values)
