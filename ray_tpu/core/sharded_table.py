"""Sharded hot tables + secondary indexes for the control plane.

The GCS keeps its hot state (KV, actor table) in plain dicts; past ~10^5
entries two costs surface at exactly the wrong time:

* a dict resize is a single stop-the-world rehash of the WHOLE table — on
  the GCS event loop that pause lands in the middle of a submission burst
  and shows up as a p99 spike on every RPC parked behind it;
* "find every entry matching X" degenerates into full-table scans, and
  the callers that need them (node death → that node's actors, job finish
  → that job's actors) run during failures/teardown when the loop is
  already busy.

:class:`ShardedTable` bounds the first: the key space hash-partitions
over N independent dicts, so any single rehash touches 1/N of the
entries, and iteration can proceed shard-at-a-time (``shard_items``)
with event-loop yields in between.  :class:`SecondaryIndex` removes the
second: O(1)-maintained reverse buckets replace the scans entirely.

Reference: the GCS in the source system is backed by sharded Redis
tables (``gcs_table_storage.cc``); this is the in-process analogue.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, Iterable, Iterator, List, Set, Tuple


class ShardedTable:
    """A mapping hash-partitioned over ``num_shards`` independent dicts.

    Same asymptotics as a dict for point ops, but worst-case single-op
    latency (rehash pause) is bounded by the largest SHARD, and iteration
    is available per shard so maintenance scans can yield between shards
    instead of holding the loop for the whole table.
    """

    __slots__ = ("_shards", "_len")

    def __init__(self, num_shards: int = 16):
        num_shards = max(1, int(num_shards))
        self._shards: List[Dict[Hashable, Any]] = [
            {} for _ in range(num_shards)]
        self._len = 0

    def _shard(self, key: Hashable) -> Dict[Hashable, Any]:
        return self._shards[hash(key) % len(self._shards)]

    # -- point ops (all O(1) amortized per SHARD) -------------------------

    def __getitem__(self, key: Hashable) -> Any:
        return self._shard(key)[key]

    def __setitem__(self, key: Hashable, value: Any) -> None:
        shard = self._shard(key)
        if key not in shard:
            self._len += 1
        shard[key] = value

    def __delitem__(self, key: Hashable) -> None:
        del self._shard(key)[key]
        self._len -= 1

    def __contains__(self, key: Hashable) -> bool:
        return key in self._shard(key)

    def get(self, key: Hashable, default: Any = None) -> Any:
        return self._shard(key).get(key, default)

    def setdefault(self, key: Hashable, default: Any = None) -> Any:
        shard = self._shard(key)
        if key not in shard:
            self._len += 1
        return shard.setdefault(key, default)

    _MISSING = object()

    def pop(self, key: Hashable, default: Any = _MISSING) -> Any:
        shard = self._shard(key)
        if key in shard:
            self._len -= 1
            return shard.pop(key)
        if default is self._MISSING:
            raise KeyError(key)
        return default

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    # -- iteration (cold paths; shard-at-a-time available) ----------------

    def __iter__(self) -> Iterator[Hashable]:
        for shard in self._shards:
            yield from shard

    def keys(self) -> Iterator[Hashable]:
        return iter(self)

    def values(self) -> Iterator[Any]:
        for shard in self._shards:
            yield from shard.values()

    def items(self) -> Iterator[Tuple[Hashable, Any]]:
        for shard in self._shards:
            yield from shard.items()

    @property
    def num_shards(self) -> int:
        return len(self._shards)

    def shard_items(self, index: int) -> Iterable[Tuple[Hashable, Any]]:
        """Snapshot of ONE shard's items — incremental scans iterate shard
        ``i`` of ``num_shards`` per tick and yield the loop in between."""
        return list(self._shards[index].items())

    def to_dict(self) -> Dict[Hashable, Any]:
        """Flat copy (persistence snapshots / debug)."""
        out: Dict[Hashable, Any] = {}
        for shard in self._shards:
            out.update(shard)
        return out


class SecondaryIndex:
    """Reverse bucket index: group key -> set of primary keys.

    Replaces "scan the whole table for entries whose field == X" with an
    O(bucket) lookup; maintenance is O(1) per add/discard/move.  Empty
    buckets are dropped eagerly so the index's size tracks the LIVE
    grouping, not its history.
    """

    __slots__ = ("_buckets",)

    def __init__(self):
        self._buckets: Dict[Hashable, Set[Hashable]] = {}

    def add(self, group: Hashable, key: Hashable) -> None:
        if group is None:
            return
        self._buckets.setdefault(group, set()).add(key)

    def discard(self, group: Hashable, key: Hashable) -> None:
        if group is None:
            return
        bucket = self._buckets.get(group)
        if bucket is not None:
            bucket.discard(key)
            if not bucket:
                del self._buckets[group]

    def move(self, old_group: Hashable, new_group: Hashable,
             key: Hashable) -> None:
        if old_group == new_group:
            return
        self.discard(old_group, key)
        self.add(new_group, key)

    def get(self, group: Hashable) -> Set[Hashable]:
        """Snapshot copy (callers mutate the table while iterating)."""
        return set(self._buckets.get(group, ()))

    def __len__(self) -> int:
        return len(self._buckets)
