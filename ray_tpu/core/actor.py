"""Actors: ActorClass / ActorHandle / ActorMethod.

Reference: ``python/ray/actor.py`` — ``ActorClass`` (:384), ``ActorClass._remote``
(:667; ``max_restarts``/``max_task_retries`` :333-352), ``ActorHandle`` (:1025).
Creation goes through the GCS (centralized, fault-tolerant); method calls are
peer-to-peer RPC to the actor's worker (reference call stack §3.3 of SURVEY).
"""

from __future__ import annotations

import hashlib
import inspect
from typing import Any, Dict, List, Optional

from . import serialization
from .common import (STREAMING_RETURNS, TaskSpec, build_spec_from_template,
                     copy_spec_into)
from .config import get_config
from .ids import ActorID, TaskID
from .object_ref import ObjectRef
from .remote_function import (_current_trace_ctx, resolve_pg_strategy,
                              serialize_args)
from .rpc import run_async

# Bound on first method submit (core_worker imports this module, so a
# top-level import would be circular).
_global_worker = None


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: int = 1, generator_backpressure: int = 0):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._generator_backpressure = generator_backpressure

    def options(self, **opts) -> "ActorMethod":
        m = ActorMethod(self._handle, self._name,
                        opts.get("num_returns", self._num_returns),
                        opts.get("generator_backpressure",
                                 self._generator_backpressure))
        return m

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(self._name, args, kwargs,
                                           self._num_returns,
                                           self._generator_backpressure)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._name}' cannot be called directly; "
            f"use '.{self._name}.remote()'.")


class ActorHandle:
    def __init__(self, actor_id: str, method_names: List[str],
                 max_task_retries: int = 0, name: Optional[str] = None):
        object.__setattr__(self, "_actor_id", actor_id)
        object.__setattr__(self, "_method_names", list(method_names))
        object.__setattr__(self, "_max_task_retries", max_task_retries)
        object.__setattr__(self, "_name", name)
        #: warm-path method-call spec templates: (method, num_returns,
        #: backpressure) -> (generation_key, template) — bounded by the
        #: actor's method count (stale generations overwrite in place)
        object.__setattr__(self, "_spec_tmpls", {})

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        if item not in self._method_names:
            raise AttributeError(
                f"actor has no method {item!r}; methods: {self._method_names}")
        return ActorMethod(self, item)

    def _submit_method(self, method: str, args, kwargs, num_returns,
                       generator_backpressure: int = 0):
        global _global_worker
        if _global_worker is None:  # deferred: core_worker imports us
            from .core_worker import global_worker as _global_worker
        w = _global_worker()
        cfg = get_config()
        if num_returns in ("streaming", "dynamic"):
            num_returns = STREAMING_RETURNS
        args_blob, arg_refs = serialize_args(args, kwargs)
        # Warm path: the method descriptor (actor id, method name, options)
        # is call-invariant — clone the cached template (pooled slot copy)
        # instead of running the TaskSpec ctor per call.  The generation
        # key pins it to this worker + config object (reinit/set_config
        # rebuilds in place).
        key = (method, num_returns, int(generator_backpressure or 0))
        gen = (w.worker_id, id(cfg))
        hit = self._spec_tmpls.get(key)
        if (hit is not None and hit[0] == gen
                and cfg.submit_plane_native_enabled):
            spec = build_spec_from_template(
                hit[1], TaskID.from_random(), args_blob,
                _current_trace_ctx())
        else:
            spec = TaskSpec(
                task_id=TaskID.from_random(),
                job_id=w.job_id,
                name=f"{method}",
                fn_id=None,
                args=args_blob,
                num_returns=num_returns,
                owner=w.address,
                is_actor_task=True,
                actor_id=ActorID.from_hex(self._actor_id),
                actor_method=method,
                max_retries=self._max_task_retries,
                generator_backpressure=int(generator_backpressure or 0),
                trace_ctx=_current_trace_ctx(),
            )
            if cfg.submit_plane_native_enabled:
                tmpl = TaskSpec.__new__(TaskSpec)
                copy_spec_into(spec, tmpl)
                self._spec_tmpls[key] = (gen, tmpl)
        refs = w.submit_actor_task(self._actor_id, spec, arg_refs)
        if num_returns == STREAMING_RETURNS:
            return refs  # an ObjectRefGenerator
        if num_returns == 0:
            return None
        return refs[0] if num_returns == 1 else refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_names,
                              self._max_task_retries, self._name))

    def __repr__(self):
        return f"ActorHandle({self._actor_id[:12]}, name={self._name!r})"


class ActorClass:
    def __init__(self, cls, default_options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._opts = dict(default_options or {})
        self._blob: Optional[bytes] = None
        self._fn_id: Optional[bytes] = None
        self._registered_in: set = set()
        self.__name__ = cls.__name__

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._opts)
        merged.update(opts)
        ac = ActorClass(self._cls, merged)
        ac._blob, ac._fn_id = self._blob, self._fn_id
        return ac

    def bind(self, *args, **kwargs):
        """Lazy DAG node: the actor is created at dag.execute() time
        (reference: dag/class_node.py)."""
        from ..dag import ClassNode
        return ClassNode(self, args, kwargs)

    def _ensure_registered(self, worker) -> bytes:
        if self._blob is None:
            self._blob = serialization.dumps_function(self._cls)
            self._fn_id = hashlib.sha1(self._blob).digest()[:16]
        key = id(worker)
        if key not in self._registered_in:
            run_async(worker.gcs.call_retry(
                "kv_put", ns="funcs", key=self._fn_id.hex(),
                value=self._blob, overwrite=False))
            self._registered_in.add(key)
        return self._fn_id

    def _method_names(self) -> List[str]:
        return [n for n, m in inspect.getmembers(self._cls)
                if callable(m) and not n.startswith("_")]

    def _is_async(self) -> bool:
        # async generator methods (streaming returns) make an actor async just
        # like coroutine methods do.
        return any(inspect.iscoroutinefunction(m)
                   or inspect.isasyncgenfunction(m)
                   for _, m in inspect.getmembers(self._cls) if callable(m))

    def remote(self, *args, **kwargs) -> ActorHandle:
        from .core_worker import global_worker
        w = global_worker()
        fn_id = self._ensure_registered(w)
        o = self._opts
        resources = dict(o.get("resources") or {})
        resources["CPU"] = float(o.get("num_cpus", 1))
        if o.get("num_tpus"):
            resources["TPU"] = float(o["num_tpus"])
        if o.get("num_gpus"):
            resources["GPU"] = float(o["num_gpus"])
        strategy = resolve_pg_strategy(o.get("scheduling_strategy", "DEFAULT"))
        args_blob, arg_refs = serialize_args(args, kwargs)
        actor_id = ActorID.from_random()
        lifetime = o.get("lifetime")
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            job_id=w.job_id,
            name=self.__name__,
            fn_id=fn_id,
            args=args_blob,
            num_returns=1,
            resources=resources,
            owner=w.address,
            scheduling_strategy=strategy,
            is_actor_creation=True,
            actor_id=actor_id,
            max_restarts=int(o.get("max_restarts", 0)),
            max_task_retries=int(o.get("max_task_retries", 0)),
            max_concurrency=int(o.get("max_concurrency",
                                      100 if self._is_async() else 1)),
            is_async_actor=self._is_async(),
            actor_name=o.get("name"),
            namespace=o.get("namespace"),
            lifetime=lifetime,
            runtime_env=o.get("runtime_env"),
        )
        # get_if_exists resolves ATOMICALLY in the GCS register handler —
        # concurrent get-or-create callers race at the single serialization
        # point and losers receive the winner's actor id (no client-side
        # pre-check TOCTOU).
        get_if_exists = bool(o.get("get_if_exists") and o.get("name"))
        aid = w.create_actor(spec, get_if_exists=get_if_exists)
        # Stash method names in GCS so get_actor() can rebuild handles.
        run_async(w.gcs.call_retry(
            "kv_put", ns="actor_meta", key=aid,
            value=serialization.dumps(
                {"methods": self._method_names(),
                 "max_task_retries": spec.max_task_retries})))
        return ActorHandle(aid, self._method_names(), spec.max_task_retries,
                           o.get("name"))

    def __call__(self, *a, **kw):
        raise TypeError(f"Actor class {self.__name__} cannot be instantiated "
                        f"directly; use {self.__name__}.remote()")


class ActorExitRequest(BaseException):
    """Raised by ``exit_actor()``; recognized by the executor as an
    INTENDED termination (BaseException so a method's broad ``except
    Exception`` cannot swallow the exit — same reasoning as SystemExit)."""


def exit_actor():
    """Terminate the current actor from inside one of its methods
    (reference: ``ray.actor.exit_actor``).  The in-flight call fails with
    a typed intended-exit ActorDiedError, the actor is marked DEAD with
    no restart (even with ``max_restarts``), and the worker process
    exits."""
    from .core_worker import global_worker_or_none
    w = global_worker_or_none()
    if w is None or w.actor_instance is None:
        raise RuntimeError("exit_actor() called outside an actor method")
    raise ActorExitRequest()


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    from .core_worker import global_worker
    w = global_worker()
    info = run_async(w.gcs.call("get_actor_info", name=name, namespace=namespace))
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"actor {name!r} not found in namespace {namespace!r}")
    meta_blob = run_async(w.gcs.call("kv_get", ns="actor_meta",
                                     key=info["actor_id"]))
    meta = serialization.loads(meta_blob) if meta_blob else {"methods": [],
                                                             "max_task_retries": 0}
    return ActorHandle(info["actor_id"], meta["methods"],
                       meta["max_task_retries"], name)
