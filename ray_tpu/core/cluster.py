"""Multi-node-cluster-in-one-machine test utility.

Reference: ``python/ray/cluster_utils.py:102`` (``Cluster`` — ``add_node`` spawns a real
raylet+workers per "node", so distributed scheduling/failover is tested without a real
cluster; SURVEY §4 calls this the load-bearing test trick).  Each added node here is a
real agent subprocess with its own worker pool and object store; ``kill_node`` is the
fault-injection hook (reference: ``NodeKillerActor``, ``test_utils.py:1401``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from .gcs import GcsServer
from .rpc import RpcClient, run_async


class ClusterNode:
    def __init__(self, proc: subprocess.Popen, node_id: str, address: str):
        self.proc = proc
        self.node_id = node_id
        self.address = address

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


class Cluster:
    """Boot a GCS + N agent subprocesses on localhost."""

    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self.session_dir = os.path.join(
            "/tmp/raytpu", f"cluster-{int(time.time() * 1000)}-{os.getpid()}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.gcs = GcsServer(session_dir=self.session_dir)
        run_async(self.gcs.start())
        self.nodes: List[ClusterNode] = []
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def address(self) -> str:
        return self.gcs.address

    def add_node(self, num_cpus: float = 2, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: int = 256 * 1024 * 1024) -> ClusterNode:
        cmd = [sys.executable, "-m", "ray_tpu.core.node_main",
               "--gcs-address", self.gcs.address,
               "--num-cpus", str(num_cpus),
               "--num-tpus", str(num_tpus),
               "--resources", json.dumps(resources or {}),
               "--labels", json.dumps(labels or {}),
               "--session-dir", self.session_dir,
               "--object-store-memory", str(object_store_memory)]
        logf = open(os.path.join(self.session_dir, "logs",
                                 f"node-{len(self.nodes)}.log"), "ab", buffering=0)
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=logf, env=env)
        line = proc.stdout.readline().decode()
        info = json.loads(line)
        node = ClusterNode(proc, info["node_id"], info["address"])
        self.nodes.append(node)
        return node

    def kill_node(self, node: ClusterNode, sigkill: bool = True):
        """Fault injection: hard-kill an agent (and its workers die with it via
        our subprocess monitoring on agent side being gone — workers become
        orphans and exit when their agent connection drops)."""
        if sigkill:
            node.proc.kill()
        else:
            node.proc.terminate()
        node.proc.wait(timeout=10)

    def wait_for_nodes(self, n: Optional[int] = None, timeout: float = 30.0):
        n = n if n is not None else len(self.nodes)
        client = RpcClient(self.gcs.address)
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                view = run_async(client.call("get_cluster_view"))
                if sum(1 for v in view.values() if v["alive"]) >= n:
                    return True
                time.sleep(0.1)
            return False
        finally:
            run_async(client.close())

    def connect_driver(self, **kwargs):
        from . import api
        return api.init(address=self.gcs.address, **kwargs)

    def shutdown(self):
        for node in self.nodes:
            if node.alive:
                node.proc.terminate()
        for node in self.nodes:
            try:
                node.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                node.proc.kill()
        run_async(self.gcs.stop(), timeout=5)
