"""ObjectRef — a future handle to a value in the distributed object store.

Mirrors the reference's ``ray.ObjectRef`` (``python/ray/_raylet.pyx`` ObjectRef class):
the ref carries its id plus the *owner's* RPC address (ownership-based object directory,
reference ``src/ray/object_manager/ownership_based_object_directory.h`` — the owner is
the source of truth for the value's location and lifetime).  Refs participate in
distributed reference counting: construction/destruction report to the process-local
ReferenceCounter (reference ``src/ray/core_worker/reference_count.h:61``).
"""

from __future__ import annotations

from typing import Optional

from .ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: str = "", _register: bool = True):
        self.id = object_id
        self.owner = owner  # rpc address of owning core worker ("" = local)
        self._registered = _register
        if _register:
            _ref_created(self)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def task_id(self):
        return self.id.task_id()

    def future(self):
        """A concurrent.futures.Future resolved with the object's value."""
        from . import api
        return api.as_future(self)

    def __await__(self):
        from . import api
        return api.get_async(self).__await__()

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Plain pickle path (e.g. sending a ref through a non-ray channel).
        # Ray-internal serialization intercepts refs via persistent_id instead
        # so it can track borrowers.
        return (ObjectRef, (self.id, self.owner, False))

    def __del__(self):
        # Only refs that incremented the count on construction decrement it
        # (refs built with _register=False, e.g. transient lookups, must not
        # unbalance the count and free live objects).
        if not getattr(self, "_registered", False):
            return
        try:
            _ref_deleted(self)
        except Exception:
            pass


# Bound on first use (core_worker imports this module, so a top-level
# import would be circular).  These run once per ObjectRef construction
# and destruction — the repeated `from .core_worker import ...` module
# machinery showed up in submit-path profiles.
_global_worker_or_none = None


def _ref_created(ref: ObjectRef):
    global _global_worker_or_none
    if _global_worker_or_none is None:
        from .core_worker import \
            global_worker_or_none as _global_worker_or_none
    w = _global_worker_or_none()
    if w is not None:
        w.reference_counter.add_local_ref(ref.id, ref.owner)


def _ref_deleted(ref: ObjectRef):
    if _global_worker_or_none is None:
        return
    w = _global_worker_or_none()
    if w is not None:
        w.reference_counter.remove_local_ref(ref.id, ref.owner)
