"""Client-side routing plane for the horizontally sharded GCS.

The control plane splits into a **router** (``core/gcs.py`` — everything
that needs global ordering: node table, jobs, actor registration, PG 2PC,
pubsub seq space) and N **shard processes** (``core/gcs_shard.py`` — the
hot, key-partitionable traffic: namespaced KV, task-event / object-event /
sched-decision fan-in rings).  This module is the one place shard
assignment is computed and the facade every runtime process talks to the
control plane through:

* :func:`shard_index` — THE partition helper.  Every cross-shard routing
  decision (client side, router proxy side, shard-side validation) goes
  through it; an AST lint (tests/test_metric_naming.py) rejects hand-hashed
  ``crc32(...) % shards`` expressions anywhere else, so client and server
  can never disagree about who owns a key.
* :class:`ShardedGcsClient` — an :class:`~ray_tpu.core.rpc.RpcClient`-
  compatible facade (``call`` / ``call_retry`` / ``notify`` / ``close`` /
  ``.address``) that sends shard-routable methods client->shard direct by
  key and everything else to the router.  The shard map is fetched lazily
  and in the background; until it arrives every call goes to the router,
  which proxies — so routing is a fast path, never a correctness
  requirement, and legacy clients (a bare RpcClient at the router address)
  keep working unchanged.

Reference: the source system's GCS is backed by sharded Redis tables
(``gcs_table_storage.cc``) with clients routed by key hash; this is the
multi-process analogue of promoting ``core/sharded_table.py``'s in-process
partition lines to process boundaries (Ray paper: the GCS "can be scaled
by sharding").
"""

from __future__ import annotations

import asyncio
import zlib
from typing import Any, Dict, List, Optional

from .config import get_config
from .rpc import RemoteError, RpcClient, RpcError


def shard_index(key: str, num_shards: int) -> int:
    """Stable shard assignment for ``key`` over ``num_shards`` shards.

    crc32, not ``hash()``: str hashing is salted per process
    (PYTHONHASHSEED), and the assignment must agree across the client,
    the router proxy, and the shard that persisted the key in a previous
    incarnation."""
    if num_shards <= 1:
        return 0
    if isinstance(key, str):
        key = key.encode()
    return zlib.crc32(key) % num_shards


#: methods partitioned by an explicit key kwarg: method -> kwarg name.
#: KV shards by NAMESPACE (not key) so ``kv_keys(ns)`` stays a one-shard
#: read and a workflow's step commits land together.
KEYED_METHODS: Dict[str, str] = {
    "kv_put": "ns",
    "kv_get": "ns",
    "kv_multi_get": "ns",
    "kv_del": "ns",
    "kv_keys": "ns",
    "kv_exists": "ns",
}

#: append-only fan-in methods: any shard is correct (reads merge across
#: all shards at the router), so each WRITER sticks to the shard its own
#: identity hashes to — one process's event stream stays ordered on one
#: shard, and the cluster's writers spread over all of them.
FANIN_METHODS = frozenset({
    "add_task_events",
    "add_object_events",
    "add_sched_decisions",
})


def shard_for(method: str, kwargs: dict, identity: str,
              num_shards: int) -> Optional[int]:
    """-> owning shard index for one call, or None for router methods."""
    if num_shards <= 0:
        return None
    key_kwarg = KEYED_METHODS.get(method)
    if key_kwarg is not None:
        key = kwargs.get(key_kwarg)
        if key is None:
            return None
        return shard_index(str(key), num_shards)
    if method in FANIN_METHODS:
        return shard_index(identity, num_shards)
    return None


class ShardedGcsClient:
    """RpcClient-compatible facade over the router + its shard processes.

    ``connections`` (config ``gcs_client_connections``) opens that many
    parallel router connections, each on its own IO-loop lane; calls
    round-robin over them (mutating calls are already idempotency-token'd,
    and nothing the runtime sends the ROUTER is order-dependent across
    calls in flight — per-connection FIFO still holds for pubsub polls,
    which always ride connection 0).  Shard connections are one per shard,
    laned round-robin.
    """

    def __init__(self, address: str, connections: int | None = None,
                 identity: str = ""):
        self.address = address
        cfg = get_config()
        n = max(1, connections if connections is not None
                else cfg.gcs_client_connections)
        self._routers: List[RpcClient] = [
            RpcClient(address, lane=(0 if i == 0 else ("lane", i)))
            for i in range(n)]
        self._rr = 0
        self._identity = identity or "owner"
        self._shard_addrs: List[str] = []
        self._shard_clients: List[RpcClient] = []
        self._map_version = 0
        self._map_requested = False
        self._closed = False

    # -- shard map ---------------------------------------------------------

    @property
    def shard_map_version(self) -> int:
        return self._map_version

    def set_shard_map(self, addrs: List[str], version: int = 0):
        """Install the shard address list (from get_shard_map, or
        piggybacked on register_node/heartbeat).  Building the per-shard
        clients is cheap; connections open lazily on first use."""
        addrs = list(addrs or [])
        self._map_version = max(self._map_version, version)
        if addrs == self._shard_addrs:
            return
        old = self._shard_clients
        self._shard_addrs = addrs
        self._shard_clients = [
            RpcClient(a, lane=(0 if i == 0 else ("lane", i)))
            for i, a in enumerate(addrs)]
        for c in old:
            try:
                asyncio.ensure_future(c.close())
            except RuntimeError:
                pass

    def apply_shard_map(self, payload: Optional[dict]):
        """Install a {"version", "shards"} piggyback payload, if any."""
        if payload:
            self.set_shard_map(payload.get("shards") or [],
                               payload.get("version") or 0)

    def _maybe_fetch_map(self):
        """Kick ONE background shard-map fetch; until it lands calls go to
        the router (which proxies, so nothing is ever wrong — just one
        hop slower)."""
        if self._map_requested or self._closed:
            return
        self._map_requested = True

        async def _fetch():
            try:
                res = await self._routers[0].call(
                    "get_shard_map", _timeout=10)
                self.apply_shard_map(res)
            except Exception:
                self._map_requested = False  # retry on a later call

        try:
            asyncio.ensure_future(_fetch())
        except RuntimeError:
            self._map_requested = False

    # -- routing -----------------------------------------------------------

    def _router(self) -> RpcClient:
        self._rr += 1
        return self._routers[self._rr % len(self._routers)]

    def _client_for(self, method: str, kwargs: dict) -> RpcClient:
        shardable = method in FANIN_METHODS or method in KEYED_METHODS
        if not shardable:
            # globally-ordered router methods are LATENCY-sensitive
            # (lease/PG/actor chains await them serially): always the
            # first connection, which lives on the caller's own loop —
            # extra connections (their lane threads, their cross-thread
            # hops) carry only the bulk shardable traffic below
            return self._routers[0]
        if self._shard_clients:
            idx = shard_for(method, kwargs, self._identity,
                            len(self._shard_clients))
            if idx is not None:
                return self._shard_clients[idx]
            return self._routers[0]
        self._maybe_fetch_map()
        return self._router()

    def _shard_failed(self):
        """A shard connection died (shard restart under its supervisor):
        drop the map so the next calls refetch, and let THIS call fall
        back to the router — the router proxies to the live replacement,
        so shard churn costs a hop, never an error."""
        self._shard_addrs = []
        self._shard_clients = []
        self._map_requested = False

    # -- RpcClient-compatible surface -------------------------------------

    async def call(self, method: str, _timeout: float | None = None,
                   **kwargs) -> Any:
        client = self._client_for(method, kwargs)
        try:
            return await client.call(method, _timeout=_timeout, **kwargs)
        except (ConnectionError, OSError, RpcError,
                asyncio.TimeoutError) as e:
            # "was this a shard connection?" must not be answered by
            # membership in self._shard_clients: a CONCURRENT call that hit
            # the same dead shard may have run _shard_failed() first and
            # cleared/rebuilt the list, making the in-flight client look
            # foreign and re-raising instead of falling back.  Router
            # clients are the stable set — anything else is a shard.
            if client not in self._routers and not isinstance(
                    e, RemoteError):
                self._shard_failed()
                return await self._router().call(
                    method, _timeout=_timeout, **kwargs)
            raise

    async def call_retry(self, method: str, _timeout: float | None = None,
                         _attempts: int | None = None,
                         _idempotent: bool = True, **kwargs) -> Any:
        client = self._client_for(method, kwargs)
        try:
            return await client.call_retry(
                method, _timeout=_timeout, _attempts=_attempts,
                _idempotent=_idempotent, **kwargs)
        except (ConnectionError, OSError, RpcError, asyncio.TimeoutError) as e:
            # same membership race as call() above: router identity, not
            # _shard_clients membership, decides the fallback.
            if client not in self._routers and not isinstance(
                    e, RemoteError):
                self._shard_failed()
                return await self._router().call_retry(
                    method, _timeout=_timeout, _attempts=_attempts,
                    _idempotent=_idempotent, **kwargs)
            raise

    async def notify(self, method: str, **kwargs):
        return await self._client_for(method, kwargs).notify(method, **kwargs)

    def call_sync(self, method: str, _timeout: float | None = None,
                  **kwargs) -> Any:
        from .rpc import run_async
        return run_async(
            self.call(method, _timeout=_timeout, **kwargs),
            timeout=(_timeout or get_config().rpc_call_timeout_s) + 5)

    async def close(self):
        self._closed = True
        for c in self._routers + self._shard_clients:
            try:
                await c.close()
            except Exception:
                pass
