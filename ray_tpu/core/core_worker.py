"""CoreWorker — the per-process runtime embedded in the driver and every worker.

Equivalent of the reference's ``CoreWorker`` (``src/ray/core_worker/core_worker.h:285``),
the single façade behind the public API:

* **Ownership** — every object created here is owned by this process; the owner is the
  source of truth for the value (small objects), its locations (large objects), and its
  lifetime via distributed refcounting (reference: ``reference_count.h:61``,
  ``ownership_based_object_directory.h``).
* **Task submission** — lease-based direct task transport: pick a node from the gossiped
  cluster view, request a worker lease (with spillback), push tasks straight to the
  leased worker over RPC, reuse leases per scheduling key (reference:
  ``direct_task_transport.h:75``, ``SchedulingKey`` lease reuse :151).
* **Task management** — pending-task table with automatic retries and lineage kept for
  reconstruction of lost objects (reference: ``task_manager.h``,
  ``object_recovery_manager.h:41``).
* **Actor calls** — direct peer-to-peer RPC to the actor's worker with per-handle
  sequence numbers; restart-aware resubmission (reference:
  ``direct_actor_task_submitter.h:68``).
* **Execution** — in worker processes, tasks run on the *main* thread (important for
  jax/TPU: the runtime owns the device in one thread); async actors run on a private
  event loop; threaded actors use a bounded pool (reference: scheduling queues +
  ``BoundedExecutor``/fiber concurrency groups, ``thread_pool.h:36``).
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import os
import pickle
import queue as _queue
import random
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import object_explain, sched_explain, serialization, spec_cache
from .object_explain import ObjectEvent
from .sched_explain import PendingReason
from .common import (STREAMING_RETURNS, ActorDiedError, GetTimeoutError,
                     NodeAffinitySchedulingStrategy, ObjectLostError,
                     OutOfMemoryError, PlacementGroupSchedulingStrategy,
                     RayTpuError, TaskError, TaskSpec, WorkerCrashedError,
                     _TopLevelRef, recycle_spec)
from . import common as _common
from .config import get_config
from .generator import ObjectRefGenerator, StreamState
from .ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from .object_ref import ObjectRef
from .object_store import ErrorRecord, MemoryStore, PlasmaRecord, ShmReader, ShmSegment
from .rpc import (ClientPool, ConnectionLost, RemoteError, RpcClient,
                  RpcError, RpcServer, get_loop, run_async)
from .runtime_context import _task_context
from .scheduling import NodeView, pick_node
from ray_tpu.util import tracing as _tracing

_global_worker: Optional["CoreWorker"] = None
_global_lock = threading.Lock()

# Canonical serialized-empty-args blob, bound on first executor use (the
# per-task compare in _resolve_args must not re-derive it per call).
_EMPTY_ARGS_BLOB: Optional[bytes] = None

# Lazy singleton: the task-lifecycle stage histogram (submit->dispatch
# queueing on the owner side; dep-fetch / arg-deserialize / execute /
# result-put on the executor side).  Shared by every CoreWorker in the
# process; the registry flush ships it to the node agent's /metrics.
_stage_keys: Dict[str, tuple] = {}


def _build_stage_hist():
    from ray_tpu.util.metrics import Histogram
    return Histogram("raytpu_task_stage_seconds",
                     "task lifecycle stage wall-clock seconds by stage",
                     tag_keys=("stage",))


_stage_hist_get: Any = None


def _task_stage_seconds():
    global _stage_hist_get
    if _stage_hist_get is None:
        # deferred to first call: importing util.metrics at module import
        # time re-enters the ray_tpu package init (circular import)
        from ray_tpu.util.metrics import lazy
        _stage_hist_get = lazy(_build_stage_hist)
    return _stage_hist_get()


def _observe_stage(stage: str, dur: float):
    """Observe one stage duration with a precomputed tags key — this is on
    the per-task hot path (several observations per task)."""
    hist = _task_stage_seconds()
    if hist is None:
        return
    key = _stage_keys.get(stage)
    if key is None:
        key = _stage_keys[stage] = (("stage", stage),)
    hist.observe_key(key, max(0.0, dur))


class _ReadPin:
    """Consumer-side half of the store's pin/release protocol: one pin taken
    by ``fetch_object(pin=True)``, released when the LAST zero-copy buffer
    view deserialized over the pinned mapping is garbage-collected (the
    lease-carrying buffer exporters in ``serialization._attach_lease`` hold
    the only other references).  Release is idempotent and GC-safe: it only
    schedules a fire-and-forget notify onto the IO loop."""

    __slots__ = ("_worker", "_oid", "_released")

    def __init__(self, worker: "CoreWorker", oid: ObjectID):
        self._worker = worker
        self._oid = oid
        self._released = False

    def release(self):
        if self._released:
            return
        self._released = True
        self._worker.release_read_pin(self._oid)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


def global_worker() -> "CoreWorker":
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_worker


def global_worker_or_none() -> Optional["CoreWorker"]:
    return _global_worker


def set_global_worker(w: Optional["CoreWorker"]):
    global _global_worker
    with _global_lock:
        _global_worker = w


def _task_retry_delay(retry_count: int) -> float:
    """Exponential backoff with a cap and jitter for task retries
    (reference: the ``task_retry_delay_ms`` family).  Retry n sleeps
    ~``base * backoff**(n-1)`` capped at ``task_retry_max_delay_s``;
    the 50-100% jitter keeps a node loss from synchronizing every owner's
    retry storm onto the survivors at the same instant."""
    cfg = get_config()
    delay = min(cfg.task_retry_max_delay_s,
                cfg.task_retry_delay_s
                * (cfg.task_retry_backoff ** max(0, retry_count - 1)))
    return delay * random.uniform(0.5, 1.0)


class _AdmissionGate:
    """Owner-side submission admission control (the scale-envelope gate).

    Bounds tasks in flight (submitted, not yet finished/failed) per
    CoreWorker at ``submit_inflight_limit``: a driver firing 1M
    ``.remote()`` calls degrades to smooth pipelining at the window
    instead of building a million specs of owner-side state and flooding
    every agent's lease queue.  The gate is WAITABLE — a full window
    parks the submitting thread until completions drain below the limit —
    and thread-aware: a submitter already running on an asyncio loop
    (the RPC IO loop processes the very completions that would free the
    window; actor loops must stay live) is never parked, only counted.
    """

    __slots__ = ("_cond", "_inflight", "_waiting", "blocked_total")

    def __init__(self):
        self._cond = threading.Condition()
        self._inflight = 0
        self._waiting = 0
        #: times a submission had to park (observability / tests)
        self.blocked_total = 0

    @property
    def inflight(self) -> int:
        return self._inflight

    def acquire(self, worker: "CoreWorker",
                spec: Optional[TaskSpec] = None) -> None:
        limit = get_config().submit_inflight_limit
        with self._cond:
            if limit <= 0 or self._inflight < limit:
                self._inflight += 1
                return
        # Window full.  Parking an event-loop thread would deadlock (the
        # loop processes the completions that drain the window) — count
        # and proceed; backpressure still lands on plain driver threads,
        # which is where million-task bursts come from.
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            with self._cond:
                self._inflight += 1
            return
        # About to park: stamp the typed reason onto the event plane so
        # "why is my .remote() slow" is answerable from raytpu explain /
        # summarize_tasks (the happy path above stamps nothing).
        if spec is not None:
            worker.pending_reason(spec, PendingReason.ADMISSION_GATE)
        # Worker-mode submitters release their lease's resources while
        # parked (same contract as blocking in ray.get) so nested tasks
        # can still run on the node.
        worker._on_block()
        try:
            with self._cond:
                self._waiting += 1
                self.blocked_total += 1
                try:
                    while (self._inflight >= limit
                           and not worker._shutdown):
                        self._cond.wait(timeout=0.2)
                finally:
                    self._waiting -= 1
                self._inflight += 1
        finally:
            worker._on_unblock()

    def release(self, n: int = 1) -> None:
        with self._cond:
            self._inflight -= n
            if self._waiting:
                self._cond.notify_all()


# ---------------------------------------------------------------------------
# Reference counting (reference: src/ray/core_worker/reference_count.h:61)
# ---------------------------------------------------------------------------

class ReferenceCounter:
    def __init__(self, worker: "CoreWorker"):
        self._w = worker
        self._lock = threading.Lock()
        self.local: Dict[ObjectID, int] = collections.defaultdict(int)
        self.submitted: Dict[ObjectID, int] = collections.defaultdict(int)
        self.borrowers: Dict[ObjectID, int] = collections.defaultdict(int)
        # Borrowed refs for which we told the owner we hold a copy; one
        # add/remove note pair per 0->N->0 cycle of our local count
        # (reference: borrower bookkeeping in reference_count.cc).
        self._borrow_noted: set = set()

    def add_local_ref(self, oid: ObjectID, owner: str = ""):
        notify = False
        with self._lock:
            self.local[oid] += 1
            if (owner and owner != self._w.address
                    and oid not in self._borrow_noted):
                self._borrow_noted.add(oid)
                notify = True
        if notify:
            self._w.send_borrower_note(oid, owner, add=True)

    def remove_local_ref(self, oid: ObjectID, owner: str):
        with self._lock:
            self.local[oid] -= 1
            dead = self.local[oid] <= 0 and self.submitted.get(oid, 0) <= 0
            noted = False
            if dead:
                self.local.pop(oid, None)
                noted = oid in self._borrow_noted
                self._borrow_noted.discard(oid)
        if dead:
            self._dead(oid, owner, noted)

    def add_submitted(self, oid: ObjectID):
        with self._lock:
            self.submitted[oid] += 1

    def add_submitted_many(self, oids) -> None:
        """Batch increment: ONE lock acquire for a whole arg list (the warm
        submit path pays this per task; per-ref locking was ~3 acquires on
        a typical spec)."""
        with self._lock:
            submitted = self.submitted
            for oid in oids:
                submitted[oid] += 1

    def remove_submitted(self, oid: ObjectID, owner: str):
        with self._lock:
            self.submitted[oid] -= 1
            dead = self.submitted[oid] <= 0 and self.local.get(oid, 0) <= 0
            noted = False
            if dead:
                self.submitted.pop(oid, None)
                noted = oid in self._borrow_noted
                self._borrow_noted.discard(oid)
        if dead:
            self._dead(oid, owner, noted)

    def remove_submitted_many(self, pairs) -> None:
        """Batch decrement of ``(oid, owner)`` pairs under one lock acquire;
        ``_dead`` notifications fire after the lock drops (same ordering as
        the scalar path — dead refs are already popped from the maps)."""
        dead_refs = []
        with self._lock:
            submitted, local = self.submitted, self.local
            for oid, owner in pairs:
                submitted[oid] -= 1
                if submitted[oid] <= 0 and local.get(oid, 0) <= 0:
                    submitted.pop(oid, None)
                    noted = oid in self._borrow_noted
                    self._borrow_noted.discard(oid)
                    dead_refs.append((oid, owner, noted))
        for oid, owner, noted in dead_refs:
            self._dead(oid, owner, noted)

    def _dead(self, oid: ObjectID, owner: str, noted: bool):
        if owner and owner != self._w.address:
            if noted:
                self._w.send_borrower_note(oid, owner, add=False)
        else:
            self._w.on_ref_count_zero(oid, owner)

    def add_borrower(self, oid: ObjectID):
        with self._lock:
            self.borrowers[oid] += 1

    def remove_borrower(self, oid: ObjectID):
        with self._lock:
            self.borrowers[oid] -= 1
            dead = self.borrowers[oid] <= 0
            if dead:
                self.borrowers.pop(oid, None)
        if dead:
            self._w.on_ref_count_zero(oid, "")

    def has_any_ref(self, oid: ObjectID) -> bool:
        with self._lock:
            return (self.local.get(oid, 0) > 0 or self.submitted.get(oid, 0) > 0
                    or self.borrowers.get(oid, 0) > 0)

    def summary(self) -> Dict[str, dict]:
        """Per-object refcount snapshot (the ``raytpu memory`` data source):
        {object_id_hex: {local, submitted, borrowers}}."""
        with self._lock:
            oids = set(self.local) | set(self.submitted) | set(self.borrowers)
            return {oid.hex(): {"local": self.local.get(oid, 0),
                                "submitted": self.submitted.get(oid, 0),
                                "borrowers": self.borrowers.get(oid, 0)}
                    for oid in oids}


# ---------------------------------------------------------------------------
# Task manager (reference: src/ray/core_worker/task_manager.h)
# ---------------------------------------------------------------------------

@dataclass
class PendingTask:
    spec: TaskSpec
    retries_left: int
    arg_refs: List[ObjectRef] = field(default_factory=list)
    #: holds one admission-gate slot (public submit entry points); internal
    #: resubmissions (reconstruction) bypass the gate and must not release
    gated: bool = False


def _result_contained_refs(res: tuple) -> list:
    """Contained-ref descriptors [(id_bytes, owner_addr), ...] of a result
    tuple, if the producing worker attached them.

    Result tuple shapes: ("inline", bytes[, contained]),
    ("plasma", size, locations[, contained]), ("error", blob).
    """
    if res[0] == "inline" and len(res) >= 3:
        return res[2]
    if res[0] == "plasma" and len(res) >= 4:
        return res[3]
    return []


class TaskManager:
    def __init__(self, worker: "CoreWorker"):
        self._w = worker
        self.pending: Dict[TaskID, PendingTask] = {}
        self.lineage: "collections.OrderedDict[TaskID, TaskSpec]" = collections.OrderedDict()
        self.num_finished = 0
        self.num_failed = 0
        #: memory-monitor kills per task (reference task_oom_retries budget)
        self.oom_kill_counts: Dict[TaskID, int] = {}

    def note_oom_kill(self, task_id: TaskID) -> int:
        n = self.oom_kill_counts.get(task_id, 0) + 1
        self.oom_kill_counts[task_id] = n
        return n

    def add_pending(self, spec: TaskSpec, arg_refs: List[ObjectRef],
                    gated: bool = False):
        self.pending[spec.task_id] = PendingTask(spec, spec.max_retries,
                                                 arg_refs, gated=gated)
        if arg_refs:
            self._w.reference_counter.add_submitted_many(
                [r.id for r in arg_refs])

    def _release_args(self, pt: PendingTask):
        if pt.arg_refs:
            self._w.reference_counter.remove_submitted_many(
                [(r.id, r.owner) for r in pt.arg_refs])
        pt.arg_refs = ()

    def register_result_borrows(self, oid: ObjectID, res: tuple):
        """Register borrows for ObjectRefs serialized inside a result NOW
        (at receipt), not when the user eventually deserializes them in
        ray.get: the producer's counts may hit zero right after it
        replies, and the escrow grace must only have to cover RPC
        latency — not user think-time (reference: reference_count.cc
        borrower bookkeeping; the round-1 grace-only scheme lost objects
        gotten later than ref_escrow_grace_s after production)."""
        for desc in _result_contained_refs(res):
            idbin, owner = desc[0], desc[1]
            hold_id = desc[2] if len(desc) > 2 else None
            if owner and owner != self._w.address:
                self._w.register_contained_borrow(oid, ObjectID(idbin),
                                                  owner, hold_id)
            else:
                # Our own object round-tripped through the result: pin
                # it for the RESULT's lifetime (the caller may have
                # dropped its original handle already), then drop the
                # producer's hold.
                self._w.register_contained_borrow(oid, ObjectID(idbin),
                                                  "", None)
                if hold_id:
                    self._w.release_local_hold(ObjectID(idbin), hold_id)

    def complete(self, task_id: TaskID, results: List[tuple]):
        if self._complete_one(task_id, results):
            self._w.admission_gate.release()

    def complete_many(self, pairs) -> None:
        """Batch completion: the whole result batch settles with ONE
        admission-gate release (one lock acquire + one notify) instead of
        a release per task — gate wakeups coalesce with the peer's
        completion batching the same way the memory store's batch waiters
        coalesce get() wakeups."""
        gated = 0
        for task_id, results in pairs:
            gated += self._complete_one(task_id, results)
        if gated:
            self._w.admission_gate.release(gated)

    def _complete_one(self, task_id: TaskID, results: List[tuple]) -> int:
        """Settle one task; returns the number of admission-gate slots the
        CALLER must release (0 or 1) — deferred so ``complete_many`` can
        coalesce a batch's releases into one."""
        pt = self.pending.pop(task_id, None)
        self.oom_kill_counts.pop(task_id, None)
        if pt is None:
            return 0
        gated = 1 if pt.gated else 0
        self._release_args(pt)
        spec = pt.spec
        if results and results[0][0] in ("gen_done", "gen_buffered"):
            self._complete_stream(task_id, spec, results[0])
            return gated
        if spec.num_returns == STREAMING_RETURNS and results \
                and results[0][0] == "error":
            # The generator body raised: the error is the stream's last item
            # (any yields that streamed before the raise stay consumable).
            st = self._w.streams.get(task_id)
            if st is not None:
                self._w.memory_store.put(
                    ObjectID.for_task_return(task_id, st.available),
                    # third element marks runtime-recorded faults (e.g. an
                    # exit_actor inside a generator) — keep them typed
                    ErrorRecord(results[0][1],
                                results[0][2] if len(results[0]) > 2
                                else False))
                st.available += 1
                st.total = st.available
                st.signal()
                if st.replay:
                    # Failed reconstruction replay: no consumer to pop it
                    # (same cleanup as the success and fail() paths).
                    self._w.streams.pop(task_id, None)
            self.num_failed += 1
            self._w.task_event(spec, "FAILED")
            return gated
        for i, res in enumerate(results):
            oid = ObjectID.for_task_return(task_id, i)
            self._w.store_task_result(oid, res)
            self.register_result_borrows(oid, res)
        self.num_finished += 1
        in_lineage = False
        if get_config().lineage_reconstruction_enabled and any(
                r[0] == "plasma" for r in results):
            self.lineage[task_id] = spec
            in_lineage = True
            while len(self.lineage) > 10000:
                self.lineage.popitem(last=False)
        self._w.task_event(spec, "FINISHED")
        # Spec recycling: settled, out of every owner-side structure, never
        # referenced again past this point — back to the free list for the
        # next submission to reuse (only plain pooled task specs; lineage
        # holds the spec for reconstruction, streams/actor-creation specs
        # have longer lives).
        cfg = get_config()
        if (cfg.submit_plane_native_enabled and cfg.spec_freelist_max > 0
                and not in_lineage and not spec.is_actor_creation
                and spec.num_returns != STREAMING_RETURNS):
            recycle_spec(spec, cfg.spec_freelist_max)
        return gated

    def _complete_stream(self, task_id: TaskID, spec: TaskSpec, res: tuple):
        """A streaming task finished: fix the stream's final length.
        ("gen_buffered", [...]) is the no-live-writer fallback — yields
        arrive here all at once instead of having streamed."""
        st = self._w.streams.get(task_id)
        if res[0] == "gen_buffered":
            for i, r in enumerate(res[1]):
                self._w._on_gen_yield(task_id, i, r, "")
            total = len(res[1])
        else:
            total = res[1]
        self.num_finished += 1
        if st is not None:
            st.total = total
            st.signal()
            if st.any_plasma and get_config().lineage_reconstruction_enabled:
                self.lineage[task_id] = spec
                while len(self.lineage) > 10000:
                    self.lineage.popitem(last=False)
            if st.replay:
                # Reconstruction replay: no consumer will ever pop it.
                self._w.streams.pop(task_id, None)
        self._w.task_event(spec, "FINISHED")

    def fail(self, task_id: TaskID, exc: BaseException, tb: str = ""):
        pt = self.pending.pop(task_id, None)
        self.oom_kill_counts.pop(task_id, None)
        if pt is None:
            return
        if pt.gated:
            self._w.admission_gate.release()
        self._release_args(pt)
        # fail() is only reached for runtime-detected faults (worker death,
        # OOM kill, retries exhausted) — never for a task body's own raise,
        # which ships through the ("error", blob) result path.
        err = ErrorRecord(pickle.dumps((exc, tb)), system=True)
        for i in range(pt.spec.num_returns):
            self._w.memory_store.put(ObjectID.for_task_return(task_id, i), err)
        st = self._w.streams.get(task_id)
        if st is not None:
            # Streaming semantics: the error becomes the stream's LAST item —
            # next() returns a ref whose get raises, then StopIteration
            # (matches the reference's generator error delivery).
            self._w.memory_store.put(
                ObjectID.for_task_return(task_id, st.available), err)
            st.available += 1
            st.total = st.available
            st.signal()
            if st.replay:
                # Failed reconstruction replay: no consumer exists to pop it.
                self._w.streams.pop(task_id, None)
        self.num_failed += 1
        self._w.task_event(pt.spec, "FAILED", error=repr(exc))

    def can_retry(self, task_id: TaskID) -> bool:
        pt = self.pending.get(task_id)
        return pt is not None and pt.retries_left != 0

    def use_retry(self, task_id: TaskID,
                  consume: bool = True) -> Optional[TaskSpec]:
        """Negative retries_left means retry forever (max_retries=-1, same
        semantics as the reference's infinite task/actor retries).

        ``consume=False`` re-queues without spending the generic budget —
        used for memory-monitor kills, which have their own bounded
        ``task_oom_retries`` budget (reference: OOM retries are counted
        separately from application failures)."""
        pt = self.pending.get(task_id)
        if pt is None or pt.retries_left == 0:
            return None
        if consume and pt.retries_left > 0:
            pt.retries_left -= 1
        pt.spec.retry_count += 1
        st = self._w.streams.get(task_id)
        if st is not None:
            # The retried generator replays from yield 0; unconsumed indexes
            # will be overwritten as the fresh run re-produces them.
            st.reset_for_retry()
        return pt.spec


# ---------------------------------------------------------------------------
# Lease pools (reference: CoreWorkerDirectTaskSubmitter)
# ---------------------------------------------------------------------------

@dataclass
class LeasedWorker:
    address: str
    worker_id: str
    lease_id: str
    node_id: str
    agent_address: str
    busy: bool = False
    idle_since: float = field(default_factory=time.monotonic)
    return_scheduled: bool = False
    #: tasks completed under this lease (``lease_reuse_max_tasks`` bound)
    tasks_done: int = 0


class LeasePool:
    """One per scheduling key: queue of tasks + leased workers executing them."""

    MAX_LEASES = 64

    def __init__(self, worker: "CoreWorker", key: tuple, resources: Dict[str, float],
                 strategy, bundle: Optional[Tuple[str, int]],
                 runtime_env: Optional[dict] = None):
        self.w = worker
        self.key = key
        self.resources = resources or {"CPU": 1.0}
        self.strategy = strategy
        self.bundle = bundle
        self.runtime_env = runtime_env
        self.queue: collections.deque[TaskSpec] = collections.deque()
        self.leased: Dict[str, LeasedWorker] = {}
        self.requesting = 0
        # Hard node affinity (soft=False) pins execution to ONE node: the
        # lease request must PARK at that agent when it is saturated, never
        # accept a spillback target — following one would silently run the
        # task on the wrong node (e.g. another pool's pipelined spare lease
        # transiently holding the target's last CPU).
        self.hard_affinity = (isinstance(strategy,
                                         NodeAffinitySchedulingStrategy)
                              and not strategy.soft)
        #: human label for decision records (first submitted task's name —
        #: the scheduling key itself is an opaque fn-id hash)
        self.label: Optional[str] = None
        # decision-record rate limiting: identical consecutive outcomes
        # (a stuck pool re-picking every 0.5 s) record the transition plus
        # a periodic heartbeat, not one record per attempt
        self._last_outcome: Optional[str] = None
        self._outcome_repeats = 0

    def submit(self, spec: TaskSpec):
        self.queue.append(spec)
        self._pump()

    # ---------------------------------------------------- explain plane

    def _note_reason(self, reason: str, **detail):
        """Stamp the typed pending reason onto (a bounded prefix of) the
        queued specs — called on TRANSITIONS only (per-task dedup lives in
        pending_reason), so the happy path never sees this."""
        cap = get_config().sched_explain_stamp_max
        for i, spec in enumerate(self.queue):
            if cap > 0 and i >= cap:
                break
            self.w.pending_reason(spec, reason, **detail)

    def _decision(self, outcome: str, explain: Optional[dict] = None,
                  node: Optional[str] = None, **extra):
        """Append one structured decision record to the owner's bounded
        buffer (flushed to the GCS ring with the task-event cadence).
        Consecutive identical outcomes are coalesced: the transition
        records, repeats keep a periodic heartbeat (every 10th)."""
        if not get_config().task_events_enabled:
            return
        if outcome == self._last_outcome:
            self._outcome_repeats += 1
            if self._outcome_repeats % 10:
                return
        else:
            self._last_outcome = outcome
            self._outcome_repeats = 0
        rec = {
            "ts": time.time(), "kind": "task",
            "label": self.label or "?",
            "demand": dict(self.resources),
            "strategy": str(self.strategy),
            "outcome": outcome, "node": node,
            "task_ids": [s.task_id.hex() for s in
                         itertools.islice(self.queue, 5)],
            "task_count": len(self.queue),
            **extra}
        if explain:
            rec["candidates"] = explain.get("candidates")
            rec.update(sched_explain.bound_rejected(
                explain.get("rejected")))
        self.w._sched_decisions.append(rec)

    def _stamp_lease_queued(self, node: Optional[str], addr: str):
        """call_later callback: the lease request has been outstanding past
        ``sched_pending_stamp_after_s`` — it is parked in the agent's lease
        queue (or the agent is saturated), so the queued tasks are now
        observably LEASE_QUEUED rather than in a fast grant."""
        if not self.queue:
            return
        self._note_reason(PendingReason.LEASE_QUEUED, node=node or addr)
        self._decision("queued", node=node or addr)

    def _pump(self):
        # Dispatch queued tasks to idle leased workers.  Multiple queued
        # tasks ride one push RPC (up to max_tasks_in_flight_per_worker),
        # split evenly across idle workers so batching never costs
        # parallelism (reference: direct_task_transport.h:151 pipelining).
        idle = [lw for lw in self.leased.values() if not lw.busy]
        cfg = get_config()
        # submit_batching_enabled=False is the scale-envelope A/B off arm:
        # one task per push RPC, one lease per request RPC.
        max_batch = (cfg.max_tasks_in_flight_per_worker
                     if cfg.submit_batching_enabled else 1)
        while self.queue and idle:
            # Split the queue over EXPECTED capacity (idle workers + leases
            # still being granted), not just current idle workers: batching
            # must never serialize onto one worker what in-flight leases
            # would have parallelized (long tasks would lose whole-node
            # parallelism; reference work-stealing solves the same hazard,
            # direct_task_transport.h:151).  Intra-batch dependencies are
            # fine: each task's result is STREAMED back as it completes
            # (handle_push_task_batch), so a consumer later in the batch
            # resolves its producer without waiting for the batch reply.
            avail = len(idle) + self.requesting
            share = min(max_batch,
                        -(-len(self.queue) // max(1, avail)))  # ceil div
            lw = idle.pop()
            batch = [self.queue.popleft()
                     for _ in range(min(share, len(self.queue)))]
            lw.busy = True
            asyncio.ensure_future(self._run_on(lw, batch))
        # Request more leases only for demand not already covered by idle
        # leased workers or in-flight lease requests.  When there IS unmet
        # demand, pipeline: ask for ``lease_pipeline_window`` leases beyond
        # the deficit so the next burst finds a granted worker instead of
        # paying a lease round trip.  Same-tick demand coalesces into
        # batched ``request_worker_leases`` RPCs of up to submit_batch_max.
        deficit = len(self.queue) - len(idle) - self.requesting
        if deficit > 0:
            deficit += max(0, cfg.lease_pipeline_window)
        want = min(deficit, self.MAX_LEASES - len(self.leased) - self.requesting)
        lease_batch_max = (max(1, cfg.submit_batch_max)
                           if cfg.submit_batching_enabled else 1)
        while want > 0:
            batch = min(want, lease_batch_max)
            want -= batch
            self.requesting += batch
            asyncio.ensure_future(self._acquire_leases(batch))
        # Return leases that ended up idle with nothing queued (covers leases
        # granted after the queue drained).
        if not self.queue:
            for lw in idle:
                if not lw.return_scheduled:
                    lw.return_scheduled = True
                    asyncio.ensure_future(self._maybe_return(lw))

    async def _acquire_leases(self, count: int):
        """Acquire up to ``count`` leases with ONE batched
        ``request_worker_leases`` RPC per attempt — a same-tick submission
        burst's whole lease demand rides a single control-plane round trip
        instead of one RPC per lease.  Spillback/infeasible replies
        retarget exactly like the old single-lease loop; a partial grant
        returns what it got and lets the next ``_pump`` re-evaluate the
        remaining deficit against the (possibly drained) queue."""
        granted = 0
        try:
            target_addr = None
            target_nid = None
            hops = 0
            while not self.w._shutdown and granted < count:
                if not self.queue:
                    # Demand drained (idle workers ate the queue, or a grant
                    # that parked at the agent came back late): STOP
                    # acquiring.  Without this exit a batch that can never
                    # fill its count keeps cycling grant->idle-return->grant
                    # forever, pinning the node's capacity.
                    return
                try:
                    view = await self.w.get_cluster_view()
                except Exception:
                    if self.w._shutdown:
                        return
                    await asyncio.sleep(0.2)
                    continue
                if target_addr is None:
                    # explain only when the event plane will carry it —
                    # the None path keeps pick_node's promise that
                    # un-observed picks pay nothing extra
                    explain = ({} if get_config().task_events_enabled
                               else None)
                    nid = pick_node(view, self.resources, self.strategy,
                                    local_node_id=self.w.node_id,
                                    explain=explain)
                    if nid is None:
                        # Infeasible right now: stamp the typed reason
                        # (NO_RESOURCES, or NODE_DRAINING when the only
                        # would-be hosts are draining), record the
                        # decision with its per-node rejection causes, and
                        # surface the demand shape to the GCS so the
                        # autoscaler can see it (reference: infeasible
                        # tasks show up in cluster load) — then wait.
                        reason = sched_explain.reason_for_no_node(explain)
                        self._note_reason(reason)
                        self._decision("no_node", explain=explain,
                                       reason=reason)
                        try:
                            await self.w.gcs.call(
                                "report_pending_demand",
                                reporter=self.w.address,
                                shape=self.resources,
                                count=max(len(self.queue), 1))
                        except Exception:
                            pass
                        await asyncio.sleep(0.5)
                        if not self.queue:
                            return
                        continue
                    target_addr = view[nid].address
                    target_nid = nid
                agent = self.w.agent_clients.get(target_addr)
                # LEASE_QUEUED is stamped LAZILY: only a request still
                # unanswered after sched_pending_stamp_after_s marks the
                # queue as parked at the agent — a fast grant pays one
                # timer arm/cancel, never a per-task event.
                stamp_h = None
                stamp_after = get_config().sched_pending_stamp_after_s
                if stamp_after > 0 and get_config().task_events_enabled:
                    stamp_h = asyncio.get_event_loop().call_later(
                        stamp_after, self._stamp_lease_queued,
                        target_nid, target_addr)
                try:
                    # Idempotent retrying lease request: a grant whose
                    # reply was lost comes back from the agent's dedup
                    # window on retry instead of leasing a SECOND worker
                    # that nothing would ever return.
                    res = await agent.call_retry(
                        "request_worker_leases",
                        count=count - granted,
                        resources=self.resources,
                        bundle=self.bundle,
                        runtime_env=self.runtime_env,
                        allow_spillback=(hops < 4
                                         and not self.hard_affinity),
                        owner=self.w.address,
                        task_label=str(self.key[0]),
                        _timeout=3600.0, _attempts=8)
                except RemoteError as e:
                    from .common import RuntimeEnvSetupError
                    if isinstance(e.cause, RuntimeEnvSetupError):
                        # Deterministic: the pool's pip env cannot be built;
                        # every queued task shares it — fail them all with
                        # the real error instead of retrying pip forever
                        # while ray.get hangs (reference:
                        # RuntimeEnvSetupError fails the task).
                        while self.queue:
                            spec = self.queue.popleft()
                            self.w.task_manager.fail(spec.task_id, e.cause,
                                                     e.remote_traceback)
                        return
                    # transient agent-side failure (register timeout etc.):
                    # back off and retry the lease
                    target_addr = target_nid = None
                    await asyncio.sleep(0.5)
                    continue
                except (RpcError, OSError):
                    # RemoteError (a subclass) is handled above; this
                    # covers ConnectionLost AND "client closed" from a
                    # pool entry force-closed under us
                    target_addr = target_nid = None
                    await asyncio.sleep(0.2)
                    continue
                finally:
                    if stamp_h is not None:
                        stamp_h.cancel()
                grants = res.get("grants") if isinstance(res, dict) else None
                if grants:
                    self._decision("granted", node=target_nid,
                                   granted=len(grants))
                    for grant in grants:
                        lw = LeasedWorker(grant["worker_address"],
                                          grant["worker_id"],
                                          grant["lease_id"],
                                          grant["node_id"], target_addr)
                        self.leased[lw.lease_id] = lw
                        granted += 1
                    if granted < count:
                        # Partial grant: the node saturated mid-batch.  Pump
                        # NOW so the granted workers start, then keep
                        # acquiring the remainder — the saturated node's
                        # slow path answers with a spillback target, which
                        # is what spreads a burst across the cluster.
                        self._pump()
                        if not self.queue:
                            return
                        continue
                    return
                if "spillback" in res:
                    self._decision("spillback", node=target_nid,
                                   spill_to=res["spillback"].get("node_id"))
                    target_addr = res["spillback"]["address"]
                    target_nid = res["spillback"].get("node_id")
                    hops += 1
                    continue
                if res.get("infeasible"):
                    self._note_reason(PendingReason.NO_RESOURCES,
                                      node=target_nid)
                    self._decision("infeasible", node=target_nid)
                    target_addr = target_nid = None
                    await asyncio.sleep(0.5)
                    continue
                if res.get("backpressure"):
                    # The agent's lease queue is at its depth bound (or the
                    # node is draining): stamp the transition, record the
                    # decision, back off for the advertised interval, then
                    # re-pick a node (the fresh cluster view may route
                    # around the hot agent; spillback spreads the rest).
                    self._note_reason(PendingReason.BACKPRESSURED,
                                      node=target_nid)
                    self._decision("backpressure", node=target_nid,
                                   retry_after_s=res.get("retry_after_s"))
                    target_addr = target_nid = None
                    await asyncio.sleep(res.get(
                        "retry_after_s",
                        get_config().lease_backpressure_retry_s))
                    continue
                # unrecognized reply shape: back off rather than spin
                target_addr = target_nid = None
                await asyncio.sleep(0.2)
        finally:
            self.requesting -= count
            self._pump()

    async def _push_specs(self, client, specs: List[TaskSpec]):
        """Ship one batch to a leased worker, wire-encoding each spec
        through the template cache (invariant portion by hash; args + ids
        per call).  The connection is established FIRST so the encoder's
        delivered-set tracks the connection these frames ride."""
        await client.ensure_connected()
        # serialization-time attribution (sched_metrics_enabled) rides
        # _timed_encode: the owner-side pickling cost per push batch is
        # one of the candidate ceilings on the single-loop submit path
        # (ROADMAP 5).  With owner_serialize_threads the encode runs on
        # the serialization pool instead of blocking this loop.
        payloads = await self.w._encode_offloaded(client, specs)
        if (len(specs) == 1
                and specs[0].num_returns != STREAMING_RETURNS):
            return [await client.call("push_task", spec=payloads[0],
                                      _timeout=86400.0)]
        # Batch RPC even for one task when it streams: only the batch
        # handler has the live writer that yield frames ride on.
        return await client.call("push_task_batch", specs=payloads,
                                 _timeout=86400.0)

    async def _run_on(self, lw: LeasedWorker, specs: List[TaskSpec]):
        client = self.w.worker_clients.get(lw.address)
        for spec in specs:
            self.w.task_event(spec, "RUNNING", node_id=lw.node_id)
        try:
            try:
                results_list = await self._push_specs(client, specs)
            except RemoteError as e:
                if not isinstance(e.cause, spec_cache.SpecCacheMiss):
                    raise
                # The worker evicted a template we thought delivered (its
                # decode raised before dispatching anything): resend once
                # with full templates.
                for spec in specs:
                    self.w.pending_reason(spec,
                                          PendingReason.SPEC_CACHE_RESEND,
                                          node=lw.node_id)
                spec_cache.SpecEncoder.forget_client(client)
                results_list = await self._push_specs(client, specs)
        except (RpcError, RemoteError, OSError) as e:
            # RpcError covers ConnectionLost AND "client closed" (the
            # pooled client force-closed by a worker-killed notification
            # racing this push) — both mean the worker is unusable
            await self._on_worker_failure(lw, specs, e)
            return
        for spec, results in zip(specs, results_list):
            if results != "__streamed__":  # else completed via push already
                self.w.task_manager.complete(spec.task_id, results)
        lw.tasks_done += len(specs)
        reuse_cap = get_config().lease_reuse_max_tasks
        if (reuse_cap > 0 and lw.tasks_done >= reuse_cap
                and lw.lease_id in self.leased):
            # Reuse bound hit: hand the worker back so one pool cannot
            # monopolise a node; the pump re-leases for remaining demand.
            self.leased.pop(lw.lease_id, None)
            try:
                agent = self.w.agent_clients.get(lw.agent_address)
                await agent.call_retry("return_worker_lease",
                                       lease_id=lw.lease_id,
                                       worker_id=lw.worker_id,
                                       worker_alive=True)
            except Exception:
                pass
        else:
            lw.busy = False
            lw.idle_since = time.monotonic()
        self._pump()

    async def _on_worker_failure(self, lw: LeasedWorker, specs: List[TaskSpec],
                                 err: Exception):
        self.leased.pop(lw.lease_id, None)
        death_cause = None
        try:
            agent = self.w.agent_clients.get(lw.agent_address)
            res = await agent.call_retry("return_worker_lease",
                                         lease_id=lw.lease_id,
                                         worker_id=lw.worker_id,
                                         worker_alive=False)
            if isinstance(res, dict):
                death_cause = res.get("death_cause")
        except Exception:
            pass
        # backstop: the killing agent may have pushed the cause directly
        # (handle_worker_killed) if the lease return raced the kill
        death_cause = death_cause or self.w._kill_causes.pop(
            lw.worker_id, None)
        retries: List[TaskSpec] = []
        oom_limit = get_config().task_oom_retries
        for spec in specs:
            if death_cause:
                # The agent killed this worker deliberately (memory
                # monitor).  OOM kills have their OWN bounded budget
                # (task_oom_retries) and do not consume the generic retry
                # budget — but an always-OOM task must FAIL with advice
                # rather than loop forever (reference: task_oom_retries +
                # the group-by-owner policy's infeasible-task escape).
                n = self.w.task_manager.note_oom_kill(spec.task_id)
                if oom_limit < 0 or n <= oom_limit:
                    retry_spec = self.w.task_manager.use_retry(
                        spec.task_id, consume=False)
                    if retry_spec is not None:
                        retries.append(retry_spec)
                        continue
                self.w.task_manager.fail(
                    spec.task_id,
                    OutOfMemoryError(
                        f"task {spec.name} was killed by the memory monitor "
                        f"{n} time(s) ({death_cause}); no retries remain "
                        f"(task_oom_retries={oom_limit}, "
                        f"max_retries={spec.max_retries}). The task's "
                        "working set appears to exceed what this node can "
                        "admit — reduce its memory footprint, raise its "
                        "resource request so fewer tasks run concurrently, "
                        "or add memory/nodes."), "")
                continue
            retry_spec = self.w.task_manager.use_retry(spec.task_id)
            if retry_spec is not None:
                retries.append(retry_spec)
            else:
                self.w.task_manager.fail(
                    spec.task_id,
                    WorkerCrashedError(f"worker {lw.worker_id[:12]} died running "
                                       f"{spec.name}: {err}"), "")
        if retries:
            # Keep ORIGINAL submission order at the queue head: batching
            # assumes queue order == dependency order (a reversed requeue
            # could batch a consumer ahead of its producer).
            self.queue.extendleft(reversed(retries))
            await asyncio.sleep(_task_retry_delay(
                max(s.retry_count for s in retries)))
            self._pump()

    async def _maybe_return(self, lw: LeasedWorker):
        try:
            await asyncio.sleep(get_config().lease_idle_return_ms / 1000.0)
        finally:
            lw.return_scheduled = False
        if lw.busy or self.queue or lw.lease_id not in self.leased:
            return
        self.leased.pop(lw.lease_id, None)
        try:
            agent = self.w.agent_clients.get(lw.agent_address)
            # token'd retry: a double-applied return would release the
            # lease's resources twice and inflate the node's capacity
            await agent.call_retry("return_worker_lease",
                                   lease_id=lw.lease_id,
                                   worker_id=lw.worker_id, worker_alive=True)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Actor submission state (per ActorHandle target)
# ---------------------------------------------------------------------------

@dataclass
class ActorTarget:
    actor_id: str
    address: Optional[str] = None
    seq: int = 0
    state: str = "PENDING"
    # Submission-ordered outbox drained by a single pump coroutine per
    # target: ordering comes from the pump being the only sender, and
    # batching comes for free (reference: per-handle sequence numbers +
    # client queueing in CoreWorkerDirectActorTaskSubmitter).
    outbox: "collections.deque[TaskSpec]" = field(
        default_factory=collections.deque)
    pump_running: bool = False


# ---------------------------------------------------------------------------
# The CoreWorker
# ---------------------------------------------------------------------------

class CoreWorker:
    def __init__(self, mode: str, gcs_address: str, agent_address: Optional[str],
                 node_id: Optional[str], job_id: Optional[JobID] = None,
                 session_dir: str = "/tmp/raytpu"):
        self.mode = mode  # "driver" | "worker"
        self.worker_id = WorkerID.from_random()
        self.job_id = job_id or JobID(b"\x00\x00\x00\x01")
        self.gcs_address = gcs_address
        self.agent_address = agent_address
        self.node_id = node_id
        self.session_dir = session_dir
        self.server = RpcServer(self, "127.0.0.1", 0)
        self.gcs: Optional[RpcClient] = None
        self.agent: Optional[RpcClient] = None
        cfg_boot = get_config()
        # Submission lanes (ROADMAP 5): worker/agent connections spread
        # (sticky per address) over agent_client_connections IO-loop
        # threads, so different peers' frame codecs and socket syscalls
        # overlap on separate OS threads.  Owner STATE stays lane-0
        # confined: laned clients' pushes hop back via _on_peer_push_routed.
        lanes = max(1, cfg_boot.agent_client_connections)
        self.agent_clients = ClientPool(lanes=lanes)
        # Worker peers stream per-task results as pushes on the batch
        # connection (see handle_push_task_batch): route them straight into
        # the task manager so a consumer elsewhere in the same batch can
        # resolve its dependency without waiting for the batch reply.
        # Single-lane pools skip the thread-routing shim entirely.
        self.worker_clients = ClientPool(
            push_handler=(self._on_peer_push if lanes == 1
                          else self._on_peer_push_routed),
            lanes=lanes)
        # Owner-side serialization pool (owner_serialize_threads): spec
        # wire-encoding for push batches runs here instead of on the RPC
        # loop, overlapping pickle time with the loop's socket work.
        if cfg_boot.owner_serialize_threads > 0:
            from concurrent.futures import ThreadPoolExecutor
            self._ser_pool = ThreadPoolExecutor(
                cfg_boot.owner_serialize_threads,
                thread_name_prefix="raytpu-ser")
        else:
            self._ser_pool = None
        self.memory_store = MemoryStore()
        self.reference_counter = ReferenceCounter(self)
        # result-object id -> [(contained oid, owner)] borrows registered at
        # task-result receipt; released when the result object is freed.
        self._contained_borrows: Dict[ObjectID, list] = {}
        # Owner-side escrow holds: oid -> {hold_id: expiry_deadline}.  Placed
        # by producers shipping our refs inside results, released by the
        # consumers that register the borrow (WaitForRefRemoved-equivalent).
        self._escrow_holds: Dict[ObjectID, Dict[str, float]] = {}
        self._hold_seq = itertools.count()
        # In-flight ADD borrower notes awaiting owner acks (see
        # flush_borrower_notes).
        self._pending_notes: set = set()
        self.task_manager = TaskManager(self)
        self.shm_reader = ShmReader()
        self.lease_pools: Dict[tuple, LeasePool] = {}
        self.actor_targets: Dict[str, ActorTarget] = {}
        # Submission coalescing: bursts of .remote() calls from the user
        # thread buffer here and drain in ONE loop callback, so the IO loop
        # wakes once per burst (not per call) and lease pools see the whole
        # burst at _pump time — which is what makes push batching effective.
        self._submit_buffer: collections.deque = collections.deque()
        self._submit_lock = threading.Lock()
        self._submit_flush_scheduled = False
        # Bounded flush window state: an armed call_later handle
        # (submit_flush_window_ms) and whether a buffer-full promotion
        # already scheduled an immediate flush for this window.
        self._submit_timer = None
        self._submit_flush_promoted = False
        # Ref-death coalescing (submit plane): dead oids buffer here and
        # drain in ONE loop callback + ONE task, so a burst of ObjectRef
        # finalizers costs one self-pipe wakeup instead of one per ref.
        self._free_buffer: list = []
        self._free_lock = threading.Lock()
        self._free_scheduled = False
        # Executor->loop reply coalescing (worker side of the same plane):
        # completed results buffer here; one loop callback resolves the
        # whole burst's futures.
        self._reply_buffer: list = []
        self._reply_lock = threading.Lock()
        self._reply_scheduled = False
        # Admission control: the waitable in-flight window every public
        # submission passes through (see _AdmissionGate).
        self.admission_gate = _AdmissionGate()
        self.fn_cache: Dict[bytes, Any] = {}
        # Submission fast path: per-(function, options) spec template
        # encoder (core/spec_cache.py) — invariant spec portions wire-encode
        # once per peer connection, each call ships only args + ids.
        self.spec_encoder = spec_cache.SpecEncoder()
        # In-flight inline->shm promotions (oid -> future): concurrent
        # borrowers of one inlined result share a single store_create.
        self._promotions: Dict[ObjectID, "asyncio.Future"] = {}
        # Streaming-generator state: owner side (task_id -> StreamState for
        # tasks WE submitted) and executor side (task_id -> _GenEmitter for
        # streaming tasks we are currently RUNNING).
        #: worker_id -> typed death cause pushed by the killing agent
        self._kill_causes: Dict[str, str] = {}
        self.streams: Dict[TaskID, "StreamState"] = {}
        self._gen_emitters: Dict[TaskID, "_GenEmitter"] = {}
        self._view_cache: Tuple[float, Dict[str, NodeView]] = (0.0, {})
        self._task_events: List[dict] = []
        #: events shed because the owner buffer hit task_events_max_buffer
        #: between flushes (a 1M-task drain must not hold 3M event dicts);
        #: _dropped is the since-last-flush delta (shipped to the GCS and
        #: reset), _shed_total the process-lifetime cumulative count
        self._task_events_dropped = 0
        self.task_events_shed_total = 0
        #: submission-plane observability: event dicts actually emitted vs
        #: suppressed by task_event_sample_n (exact counters — the sampled
        #: payload stream is a view, these are the ground truth)
        self._sp_events_emitted = 0
        self._sp_events_sampled = 0
        #: owner-side submit timestamps: the "queue" (submit->dispatch) and
        #: "total" (submit->terminal) stage durations are computed from these
        self._submit_ts: Dict[TaskID, float] = {}
        # Scheduler explain plane (core/sched_explain.py): the last typed
        # pending reason stamped per task (dedup — a backpressure retry
        # loop stamps one transition, not one event per attempt; entries
        # clear on RUNNING/terminal) and the bounded buffer of structured
        # lease-acquisition decision records flushed to the GCS ring
        # alongside task events.
        self._last_reason: Dict[TaskID, str] = {}
        self._sched_decisions: collections.deque = collections.deque(
            maxlen=512)
        # Object-plane flight recorder (core/object_explain.py): bounded
        # buffer of owner-side lifecycle transitions (CREATED/INLINED/
        # FREED) flushed to the GCS object-event ring alongside task
        # events.  Never written when object_metrics_enabled is off.
        self._object_events: collections.deque = collections.deque(
            maxlen=4096)
        # STAGES-event rate cap bookkeeping (see _record_stages)
        self._stage_event_window = 0
        self._stage_event_count = 0
        self._bg: List[asyncio.Task] = []
        # executor state (worker mode)
        self.exec_queue: "_queue.Queue[tuple]" = _queue.Queue()
        self.actor_instance: Any = None
        self.actor_spec: Optional[TaskSpec] = None
        self._actor_threadpool = None
        self._actor_async_loop: Optional[asyncio.AbstractEventLoop] = None
        self._shutdown = False
        self._blocked_depth = 0

    # ------------------------------------------------------------------ boot

    async def _start(self):
        await self.server.start()
        # Shard-aware control-plane client (core/gcs_router.py): hot
        # per-task traffic (kv, task/object/sched event flushes) goes
        # client->shard direct by key once the shard map arrives; the
        # globally-ordered methods go to the router.  With sharding off
        # this degrades to exactly the old single connection.
        from .gcs_router import ShardedGcsClient
        self.gcs = ShardedGcsClient(self.gcs_address,
                                    identity=self.worker_id.hex())
        if self.agent_address:
            self.agent = self.agent_clients.get(self.agent_address)
        if get_config().task_events_enabled or object_explain.enabled():
            # the flush loop also carries owner-side object events and
            # sched decisions, so the object plane alone keeps it alive
            self._bg.append(asyncio.ensure_future(self._flush_task_events_loop()))
        from ray_tpu.util.usage_stats import usage_stats_enabled
        if usage_stats_enabled():
            self._bg.append(asyncio.ensure_future(self._usage_flush_loop()))
        # Config-gated stall detector on the shared IO loop: driver/worker
        # asyncio stalls surface as raytpu_event_loop_lag_seconds alongside
        # the agent's and GCS's (see util/loop_monitor.install).
        from ray_tpu.util.loop_monitor import install as _install_loop_mon
        self._loop_monitor = _install_loop_mon(
            asyncio.get_event_loop(),
            f"{self.mode}:{self.worker_id.hex()[:12]}",
            gcs_call=self.gcs.call)
        return self

    async def _usage_flush_loop(self):
        """Periodically push this process's usage records to the GCS KV —
        the path by which WORKER-side library imports (a task body's
        ``import ray_tpu.train``) reach the cluster usage report
        (reference: usage_lib's worker-side record propagation).  The
        flush is a no-op unless records changed since the last push."""
        from ray_tpu.util import usage_stats
        while not self._shutdown:
            await asyncio.sleep(30.0)
            try:
                await usage_stats.flush_via(self.gcs.call, self.gcs_address)
            except Exception:
                pass

    def start(self):
        run_async(self._start())
        set_global_worker(self)
        # spans recorded before this process had a worker (driver pre-init)
        # were buffered locally — drain them into the event stream now
        from ray_tpu.util.tracing import flush_pending_spans
        flush_pending_spans()
        return self

    @property
    def address(self) -> str:
        return self.server.address

    def shutdown(self):
        self._shutdown = True
        if getattr(self, "_loop_monitor", None):
            self._loop_monitor.stop()
        if self._ser_pool is not None:
            self._ser_pool.shutdown(wait=False)

        async def _stop():
            for t in self._bg:
                t.cancel()
            await self.server.stop()
            await self.agent_clients.close_all()
            await self.worker_clients.close_all()
            if self.gcs:
                await self.gcs.close()
        try:
            run_async(_stop(), timeout=5)
        except Exception:
            pass
        self.shm_reader.close()
        if global_worker_or_none() is self:
            set_global_worker(None)

    # -------------------------------------------------------------- telemetry

    def task_event(self, spec: TaskSpec, state: str, **extra):
        cfg = get_config()
        if not cfg.task_events_enabled:
            return
        now = time.time()
        # Owner-side stage stamps: SUBMITTED->RUNNING is the scheduling/
        # queueing stage (lease acquisition + dispatch), SUBMITTED->terminal
        # is the task's whole wall clock.  Durations ride the events (the
        # timeline and summarize_tasks read them there) and feed the stage
        # histogram (the /metrics percentiles).
        if cfg.task_stage_breakdown_enabled:
            if state == "SUBMITTED":
                self._submit_ts[spec.task_id] = now
                while len(self._submit_ts) > cfg.task_events_max_buffer:
                    self._submit_ts.pop(next(iter(self._submit_ts)))
            elif state == "RUNNING":
                t0 = self._submit_ts.get(spec.task_id)
                if t0 is not None:
                    extra.setdefault("queue_s", now - t0)
                    _observe_stage("queue", now - t0)
            elif state in ("FINISHED", "FAILED"):
                t0 = self._submit_ts.pop(spec.task_id, None)
                if t0 is not None:
                    extra.setdefault("total_s", now - t0)
                    _observe_stage("total", now - t0)
        if state in ("RUNNING", "FINISHED", "FAILED"):
            # next pending episode (a retry re-queued by a worker death)
            # gets a fresh reason transition
            self._last_reason.pop(spec.task_id, None)
        # Sampled event payloads: the histograms and stage stamps above
        # observed EVERY task; the per-task SUBMITTED/RUNNING event dicts
        # ship 1-in-N when task_event_sample_n > 1.  Terminal states
        # (FINISHED/FAILED) and typed PENDING reasons always emit — so
        # summarize_tasks still counts every task (it keys on the NEWEST
        # event per task) and `raytpu explain` answers for any task that
        # reached a terminal or stuck state.  The coin is the task id's
        # last byte (the 8-byte incrementing counter tail — uniform), so a
        # task's trail is all-or-nothing, never half-sampled.
        n = cfg.task_event_sample_n
        if (n > 1 and state in ("SUBMITTED", "RUNNING")
                and spec.task_id._bin[-1] % n):
            self._sp_events_sampled += 1
            return
        self._sp_events_emitted += 1
        ev = {
            "task_id": spec.task_id.hex(), "name": spec.name, "state": state,
            "job_id": spec.job_id.hex(), "ts": now,
            "actor_id": spec.actor_id.hex() if spec.actor_id else None,
            **extra}
        if spec.trace_ctx:
            # the task's slice joins the submitter's trace: its own span id
            # derives from the task id so parent/child arrows line up
            ev.setdefault("trace_id", spec.trace_ctx[0])
            ev.setdefault("parent_id", spec.trace_ctx[1])
            ev.setdefault("span_id", spec.task_id.hex()[:12])
        self._append_task_event(ev)

    def _timed_encode(self, client, specs: List[TaskSpec]) -> list:
        """Wire-encode specs through the template cache, attributing the
        pickling time to ``raytpu_sched_owner_serialize_seconds`` (one
        observation per batch) — the owner-loop cost the saturation plane
        must separate from dispatch/flush time."""
        om = sched_explain.owner_metrics()
        t0 = time.perf_counter() if om is not None else 0.0
        payloads = None
        if len(specs) > 1:
            # Warm batches collapse into ONE packed binary frame (native
            # submission plane) — the RPC pickle sees a single bytes blob
            # instead of N nested tuples.  Ineligible batches (big args,
            # actor creations, cache off) fall back to per-spec encode.
            packed = self.spec_encoder.encode_batch(client, specs)
            if packed is not None:
                payloads = packed
        if payloads is None:
            payloads = [self.spec_encoder.encode(client, s) for s in specs]
        if om is not None:
            om["serialize"].observe(time.perf_counter() - t0)
        return payloads

    async def _encode_offloaded(self, client, specs: List[TaskSpec]) -> list:
        """Wire-encode a push batch, on the serialization pool when
        configured (owner_serialize_threads — the submission-lane split:
        pickling overlaps the loop's socket work) or inline otherwise.
        Single-spec batches stay inline: the executor hop costs more than
        a warm one-spec encode."""
        if self._ser_pool is not None and len(specs) > 1:
            return await asyncio.get_event_loop().run_in_executor(
                self._ser_pool, self._timed_encode, client, specs)
        return self._timed_encode(client, specs)

    def pending_reason(self, spec: TaskSpec, reason: str, **detail):
        """Stamp a typed pending-reason transition onto the task-event
        plane: one ``state="PENDING"`` event carrying ``reason=<constant
        from PendingReason>`` plus optional bounded detail (node id,
        cause).  Deduped per task — re-entering the same reason (a
        backpressure retry loop, repeated infeasible picks) records
        nothing, so the trail is the TRANSITION history, with timestamps.

        Reasons MUST be ``PendingReason.*`` constants (AST lint in
        tests/test_metric_naming.py): they become event fields and rollup
        keys, and a free-form string here would be an unbounded label."""
        if not get_config().task_events_enabled:
            return
        if self._last_reason.get(spec.task_id) == reason:
            return
        self._last_reason[spec.task_id] = reason
        # same ceiling discipline as _submit_ts: a flood of stuck tasks
        # must not grow this map without bound.  Unlike _submit_ts this
        # map has TWO writer threads (a gate-parked driver thread and the
        # IO loop), so eviction must tolerate losing the race for the
        # front key — never raise into a lease-acquisition task.
        while len(self._last_reason) > get_config().task_events_max_buffer:
            try:
                self._last_reason.pop(next(iter(self._last_reason)), None)
            except (StopIteration, RuntimeError, KeyError):
                break
        self.task_event(spec, "PENDING", reason=reason, **detail)

    def object_event(self, oid: ObjectID, event: str, **extra):
        """Stamp one owner-side object lifecycle transition (a constant
        from ``ObjectEvent``) onto the flight-recorder plane.  One cached
        boolean when the object plane is off; the deque bounds memory."""
        if not object_explain.enabled():
            return
        self._object_events.append({
            "object_id": oid.hex(), "event": event, "ts": time.time(),
            "owner": self.address, **extra})

    def _append_task_event(self, ev: dict):
        """Bounded owner-side event buffer: beyond task_events_max_buffer
        unflushed events, new ones are SHED (drop-newest, O(1)) and counted
        — a million-task drain keeps a flat event-memory ceiling instead of
        holding millions of dicts between flush ticks.  The shed count
        rides the next flush so the GCS can surface the gap."""
        if len(self._task_events) >= get_config().task_events_max_buffer:
            self._task_events_dropped += 1
            self.task_events_shed_total += 1
            return
        self._task_events.append(ev)

    def _record_stages(self, spec: TaskSpec, stages: Dict[str, list]):
        """Executor-side per-stage breakdown of one completed task: appends
        a STAGES task event (the timeline renders these as nested sub-slices
        inside the task's slice) and observes each duration into
        ``raytpu_task_stage_seconds``.  Runs on the executor thread;
        list.append is atomic under the GIL (same contract as span())."""
        cfg = get_config()
        if (not stages or not cfg.task_events_enabled
                or not cfg.task_stage_breakdown_enabled):
            return
        payload: Dict[str, tuple] = {}
        for name, (t0, t1) in stages.items():
            dur = max(0.0, t1 - t0)
            payload[name] = (t0, dur)
            _observe_stage(name, dur)
        # Per-task event payloads are rate-capped (histograms above are
        # not): under a small-task flood the timeline samples, instead of
        # the event pipeline eating the throughput it is measuring.
        cap = cfg.task_stage_events_per_s
        if cap > 0:
            now_s = int(time.time())
            if now_s != self._stage_event_window:
                self._stage_event_window = now_s
                self._stage_event_count = 0
            if self._stage_event_count >= cap:
                return
            self._stage_event_count += 1
        # deliberately slim (no job/actor ids): one of these ships per task
        self._append_task_event({
            "task_id": spec.task_id.hex(), "name": spec.name,
            "state": "STAGES",
            "ts": min(t0 for t0, _ in payload.values()),
            "worker": self.worker_id.hex()[:12],
            "stages": payload})

    def _submit_plane_counters(self) -> dict:
        """Exact submission-plane counters that piggyback the task-event
        flush (no extra RPC): the GCS folds the latest snapshot per owner
        into sched_stats, so ``raytpu status`` shows what sampling hid."""
        from ..native import submit_plane_loaded
        cfg = get_config()
        return {
            "owner": self.address,
            "events_emitted": self._sp_events_emitted,
            "events_sampled": self._sp_events_sampled,
            "events_shed": self.task_events_shed_total,
            "freelist_hits": _common.spec_freelist_hits,
            "freelist_misses": _common.spec_freelist_misses,
            "native_enabled": bool(cfg.submit_plane_native_enabled),
            "native_loaded": submit_plane_loaded(),
            "sample_n": int(cfg.task_event_sample_n),
        }

    async def _flush_task_events_loop(self):
        CHUNK = 10_000  # bound the per-RPC frame, not one giant pickle
        while not self._shutdown:
            await asyncio.sleep(1.0)
            if self._task_events and self.gcs:
                batch, self._task_events = self._task_events, []
                dropped, self._task_events_dropped = \
                    self._task_events_dropped, 0
                try:
                    # token'd retry: a lost reply must not double-record
                    # the batch (duplicate events skew summarize_tasks)
                    for i in range(0, len(batch), CHUNK):
                        await self.gcs.call_retry(
                            "add_task_events", events=batch[i:i + CHUNK],
                            dropped=dropped if i == 0 else 0,
                            counters=self._submit_plane_counters()
                            if i == 0 else None)
                except Exception:
                    pass
            if self._object_events and self.gcs:
                # owner-side object lifecycle events (CREATED/INLINED/
                # FREED) piggyback the task-event cadence into the GCS
                # object ring (best effort, same as decisions below)
                events = list(self._object_events)
                self._object_events.clear()
                try:
                    await self.gcs.call("add_object_events", events=events,
                                        _timeout=10)
                except Exception:
                    pass
            if self._sched_decisions and self.gcs:
                # owner-side scheduling decision records ride the same
                # cadence into the GCS ring (best effort: a lost batch
                # costs explain detail, never correctness)
                records = list(self._sched_decisions)
                self._sched_decisions.clear()
                try:
                    await self.gcs.call(
                        "add_sched_decisions", records=records, _timeout=10)
                except Exception:
                    pass

    # ---------------------------------------------------------- cluster view

    async def get_cluster_view(self) -> Dict[str, NodeView]:
        now = time.monotonic()
        ts, view = self._view_cache
        if now - ts < 0.1 and view:
            return view
        payload = await self.gcs.call_retry("get_cluster_view",
                                            _idempotent=False)
        # draining rides the view so OWNER-side pick_node routes around a
        # preempted node up front (it used to be dropped here, and clients
        # only learned via a backpressure round trip to the draining agent)
        view = {nid: NodeView(nid, d["address"], d["total"], d["available"],
                              d.get("labels", {}), d.get("alive", True),
                              d.get("queue_len", 0),
                              draining=d.get("draining", False),
                              task_leased=d.get("task_leased", {}))
                for nid, d in payload.items()}
        self._view_cache = (now, view)
        return view

    # ------------------------------------------------------------------- put

    def put(self, value: Any) -> ObjectRef:
        return run_async(self.put_async(value))

    async def put_async(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        if await self._try_zero_copy_put(oid, value):
            return ObjectRef(oid, owner=self.address)
        so = serialization.serialize(value)
        await self._store_serialized(oid, so)
        return ObjectRef(oid, owner=self.address)

    async def _try_zero_copy_put(self, oid: ObjectID, value: Any) -> bool:
        """Reserve-then-write put (the ledger's ``put/copies=0`` class):
        estimate the flat size WITHOUT pickling, reserve the arena range,
        and serialize straight into it — the pickler's out-of-band
        buffers land by parallel gather-write, the inband stream and
        header follow, and seal happens in place (no intermediate bytes,
        no serial post-hoc memcpy; see core/serialization.py).

        False when the value is small / not estimable / not
        buffer-dominated, when ``zero_copy_put_enabled`` is off, or on a
        size-estimate miss (the reservation is released) — the caller
        then takes the classic 1-copy path unchanged."""
        cfg = get_config()
        if not cfg.zero_copy_put_enabled or self.agent is None:
            return False
        bounds = serialization.estimate_flat_size(value)
        # the inline-vs-plasma threshold compares the LOWER bound: a value
        # whose exact flat size would still inline must not be pushed into
        # the shm store by a pessimistic reservation estimate
        if bounds is None or bounds[1] <= cfg.max_direct_call_object_size:
            return False
        est = bounds[0]
        res = await self.agent.call_retry("store_create", object_id=oid,
                                          size=est, owner=self.address)
        seg = ShmSegment(res["path"], est, create=False)
        try:
            landed = serialization.serialize_into(value, seg.view())
        finally:
            seg.close()
        if landed is None:
            # estimate miss: release the reservation; nothing depends on
            # the partial landing (the entry was never sealed)
            try:
                await self.agent.call_retry("store_free", object_ids=[oid])
            except Exception:
                pass
            return False
        object_explain.ledger_record(object_explain.KEY_PUT_ZC, landed.used)
        self.object_event(oid, ObjectEvent.CREATED, size=landed.used,
                          node=(self.node_id or "")[:12] or None,
                          zero_copy=True)
        # seal TRUNCATES to the exact bytes written: readers/transfers/
        # spills must never touch the reservation's slack tail (recycled
        # arena memory — another object's stale bytes)
        await self.agent.notify("store_seal", object_id=oid,
                                size=landed.used)
        self.memory_store.put(
            oid, PlasmaRecord(landed.used,
                              [(self.node_id, self.agent_address)]))
        return True

    async def _store_serialized(self, oid: ObjectID, so: serialization.SerializedObject):
        cfg = get_config()
        size = so.flat_size()
        if size <= cfg.max_direct_call_object_size or self.agent is None:
            self.memory_store.put(oid, so.to_bytes())
            object_explain.ledger_record(object_explain.KEY_PUT_INLINE,
                                         size)
            self.object_event(oid, ObjectEvent.INLINED, size=size)
        else:
            res = await self.agent.call_retry("store_create", object_id=oid,
                                              size=size, owner=self.address)
            # CREATED is stamped BEFORE the seal notify: the agent's SEALED
            # event must never carry an earlier timestamp than the owner's
            # CREATED (explain_object sorts by ts — an inverted trail would
            # render an impossible lifecycle).  The ledger's headline row
            # rides along: the put path declares ONE payload copy
            # (serialize straight into the arena mapping); the
            # zero-copy-put rewrite must move this to copies=0.
            object_explain.ledger_record(object_explain.KEY_PUT, size)
            self.object_event(oid, ObjectEvent.CREATED, size=size,
                              node=(self.node_id or "")[:12] or None)
            seg = ShmSegment(res["path"], size, create=False)
            try:
                so.write_into(seg.view())
            finally:
                seg.close()
            # One-way seal: saves a round trip per put.  Readers that race it
            # park on wait_sealed at the agent (fetch_object), and this
            # process's own later agent calls are ordered behind it on the
            # same connection.
            await self.agent.notify("store_seal", object_id=oid)
            self.memory_store.put(
                oid, PlasmaRecord(size, [(self.node_id, self.agent_address)]))

    def store_task_result(self, oid: ObjectID, res: tuple):
        """Record a task's return descriptor into the owner's memory store."""
        kind = res[0]
        if kind == "inline":
            self.memory_store.put(oid, res[1])
        elif kind == "plasma":
            self.memory_store.put(oid, PlasmaRecord(res[1], res[2]))
        elif kind == "error":
            # optional third element marks a RUNTIME-recorded fault (e.g.
            # exit_actor's intended-death record) so get raises it typed
            self.memory_store.put(oid, ErrorRecord(
                res[1], res[2] if len(res) > 2 else False))
        else:
            raise ValueError(f"bad result kind {kind}")

    # ------------------------------------------------------------------- get

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        # Fast path: every ref already resolved to an inline/error record in
        # the local memory store — deserialize on the calling thread, no IO
        # loop round trip, no block/unblock protocol (nothing waits).
        records = []
        for r in refs:
            rec = self.memory_store.get_if_exists(r.id)
            if rec is None or isinstance(rec, PlasmaRecord):
                records = None
                break
            records.append(rec)
        if records is not None:
            values = [self._inline_record_to_value(r, rec)
                      for r, rec in zip(refs, records)]
            return values[0] if single else values
        self._on_block()
        try:
            values = run_async(self.get_async_many(refs, timeout),
                               timeout=None if timeout is None else timeout + 10)
        finally:
            self._on_unblock()
        return values[0] if single else values

    def _inline_record_to_value(self, ref: ObjectRef, record):
        if isinstance(record, ErrorRecord):
            exc, tb = pickle.loads(record.error)
            if isinstance(exc, TaskError):
                raise exc
            if record.system and isinstance(exc, RayTpuError):
                # Runtime-recorded faults (OutOfMemoryError, WorkerCrashed,
                # ActorDied, …) surface typed, not wrapped — matches
                # ray.exceptions semantics.  A task BODY that lets a
                # RayTpuError propagate still wraps in TaskError below, so
                # the failure stays attributed to the raising task.
                raise exc
            raise TaskError(exc, ref.hex()[:12], tb) from None
        if record == serialization.none_bytes():
            return None
        return serialization.loads(record)

    async def get_async_many(self, refs: List[ObjectRef],
                             timeout: Optional[float] = None) -> List[Any]:
        # Batched wait for OWNED refs (the drain hot path): one shared
        # future wakes when the last result lands (MemoryStore.wait_many)
        # instead of a gather over per-ref coroutines + Events — the
        # owner-loop get machinery was one of the measured single-loop
        # ceilings (ROADMAP 5).  Borrowed refs keep the per-ref path
        # (owner round trips are genuinely per-ref).
        if (get_config().completion_batching_enabled
                and all(r.owner in ("", self.address) for r in refs)):
            ok = await self.memory_store.wait_many(
                [r.id for r in refs], timeout)
            if not ok:
                raise GetTimeoutError(
                    f"timed out waiting for {len(refs)} objects")
            records = [self.memory_store.get_if_exists(r.id) for r in refs]
            if any(isinstance(rec, PlasmaRecord) for rec in records):
                return list(await asyncio.gather(
                    *[self._record_to_value(r, rec)
                      for r, rec in zip(refs, records)]))
            return [self._inline_record_to_value(r, rec)
                    for r, rec in zip(refs, records)]
        return list(await asyncio.gather(*[self.get_async(r, timeout) for r in refs]))

    async def get_async(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        record = await self._resolve_record(ref, timeout)
        return await self._record_to_value(ref, record)

    async def _resolve_record(self, ref: ObjectRef, timeout: Optional[float]):
        oid = ref.id
        if self.memory_store.contains(oid):
            return self.memory_store.get_if_exists(oid)
        if ref.owner in ("", self.address):
            ok = await self.memory_store.wait_ready(oid, timeout)
            if not ok:
                raise GetTimeoutError(f"timed out waiting for {ref}")
            return self.memory_store.get_if_exists(oid)
        # Borrowed ref: ask the owner (it blocks until the producing task finishes).
        owner = self.worker_clients.get(ref.owner)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            step = 30.0 if deadline is None else max(0.0, deadline - time.monotonic())
            if deadline is not None and step <= 0:
                raise GetTimeoutError(f"timed out waiting for {ref}")
            try:
                # bounded retry first: a transient drop on the owner link
                # must not masquerade as owner death (ObjectLostError)
                rec = await owner.call_retry(
                    "locate_object", object_id=oid,
                    timeout=min(step, 30.0) if deadline else 30.0,
                    _timeout=(min(step, 30.0) if deadline else 30.0) + 15,
                    _attempts=3, _idempotent=False)
            except asyncio.TimeoutError:
                # slow-but-alive owner (on 3.11+ TimeoutError is an
                # OSError subclass — it must NOT read as owner death)
                raise
            except (ConnectionLost, ConnectionError, OSError):
                raise ObjectLostError(oid, f"owner {ref.owner} of {ref} died") from None
            if rec is not None:
                if rec[0] == "plasma":
                    return PlasmaRecord(rec[1], rec[2])
                if rec[0] == "inline":
                    return rec[1]
                return ErrorRecord(rec[1], rec[2] if len(rec) > 2 else False)

    async def _record_to_value(self, ref: ObjectRef, record) -> Any:
        if isinstance(record, PlasmaRecord):
            data, pin = await self._fetch_plasma(ref, record)
            so = serialization.SerializedObject.from_buffer(data)
            return serialization.deserialize(so, pin_lease=pin)
        return self._inline_record_to_value(ref, record)

    async def _fetch_plasma(self, ref: ObjectRef, record: PlasmaRecord):
        """-> (buffer, pin | None): the flattened object bytes, zero-copy
        over the pinned store mapping when the agent granted a read pin."""
        if self.agent is None:
            return await self._driver_fetch_plasma(ref, record)
        return await self._agent_fetch_plasma(ref, record)

    async def _driver_fetch_plasma(self, ref: ObjectRef,
                                   record: PlasmaRecord):
        """Agent-less driver fetch (a driver not colocated with a node
        agent): pull the whole object over RPC, landing every chunk
        readinto-style into ONE preallocated buffer via ``call_into`` —
        the reply's out-of-band bytes drain from the stream buffer
        straight into their final resting place instead of accumulating a
        ``bytes`` per reply and paying a full extra copy per object.

        The location list may contain PARTIAL holders (they register
        after their first chunk; their uncovered ranges raise a typed
        ChunkNotAvailable) and can shrink (failed pulls deregister): try
        every location, skip the unusable, reject short replies (silent
        corruption otherwise)."""
        last: Optional[BaseException] = None
        from . import external_spill
        buf = bytearray(record.size)
        mv = memoryview(buf)
        chunk = max(1, get_config().object_transfer_chunk_bytes)
        for node_id, addr in list(record.locations):
            if external_spill.is_external_address(addr):
                try:
                    data = await asyncio.get_event_loop() \
                        .run_in_executor(None, external_spill.timed_read,
                                         addr)
                except Exception as e:  # noqa: BLE001 — try next
                    last = e
                    continue
                if len(data) != record.size:
                    last = ObjectLostError(
                        ref.id, f"external copy at {addr} has "
                                f"{len(data)} of {record.size} B")
                    continue
                return data, None
            client = self.agent_clients.get(addr)
            try:
                off = 0
                while off < record.size:
                    n = min(chunk, record.size - off)
                    got = await client.call_into(
                        "read_chunk", mv[off:off + n], object_id=ref.id,
                        offset=off, length=n)
                    landed = got.nbytes if isinstance(got, memoryview) \
                        else len(got)
                    if landed != n:
                        raise ObjectLostError(
                            ref.id, f"short read_chunk reply: {landed} of "
                                    f"{n} B at offset {off} from {addr}")
                    if not isinstance(got, memoryview):
                        mv[off:off + landed] = got  # small in-band reply
                    off += n
            except Exception as e:  # noqa: BLE001 — try next holder
                last = e
                continue
            return buf, None
        raise ObjectLostError(
            ref.id, f"no usable location for {ref.id}: {last}")

    async def _agent_fetch_plasma(self, ref: ObjectRef,
                                  record: PlasmaRecord):
        try:
            # idempotent retry: a pin GRANTED on an attempt whose reply was
            # lost must come back as the same grant (one ledger entry), not
            # a second pin nobody will ever release
            res = await self.agent.call_retry("fetch_object",
                                              object_id=ref.id,
                                              size=record.size,
                                              locations=record.locations,
                                              owner=ref.owner or self.address,
                                              pin=True,
                                              pinner=self.address)
            return await self._read_fetched(ref.id, res)
        except (RemoteError, ConnectionLost):
            return await self._try_reconstruct(ref, record)

    async def _read_fetched(self, object_id: ObjectID, res: dict):
        """Read a fetched object from the local store -> (buffer, pin|None).

        Pinned fast path (the plasma-client protocol): the agent pinned the
        object before replying, so the mapping cannot be evicted or its
        arena offset recycled under us — attach a ZERO-COPY readonly view
        and hand back a pin lease that the deserialized buffers release on
        GC.  Unpinned fallback: copy out, then re-validate with the agent
        (whose loop serializes with eviction) that the object still lives
        at that path; a recycled slot re-fetches instead of returning
        another object's bytes."""
        for _ in range(3):
            if res.get("pinned"):
                # Construct the pin guard BEFORE attaching: if view() fails
                # (pool unlinked across an agent restart, mmap error), the
                # agent-side pin must still be released or the object stays
                # unevictable forever.  On failure, fall through to the
                # copy+verify path.
                pin = _ReadPin(self, object_id)
                try:
                    view = self.shm_reader.view(res["path"], res["size"])
                except OSError:
                    pin.release()
                else:
                    # copy ledger: the pinned same-host get is the plane's
                    # declared ZERO-copy path (plasma-client contract)
                    object_explain.ledger_record(object_explain.KEY_GET,
                                                 res["size"])
                    return view, pin
            try:
                data = self.shm_reader.read(res["path"], res["size"])
            except OSError:
                # Stale path — e.g. the pool file was unlinked across an
                # agent restart.  The same OSError that broke view() above
                # breaks this read too; treat it like a failed verify and
                # refetch rather than leaking a raw FileNotFoundError.
                ok = False
            else:
                if "#" not in res["path"]:
                    object_explain.ledger_record(
                        object_explain.KEY_GET, res["size"])
                    return data, None  # file-backed: unlink keeps views safe
                ok = await self.agent.call_retry("store_verify",
                                                 object_id=object_id,
                                                 path=res["path"],
                                                 _idempotent=False)
            if ok:
                object_explain.ledger_record(object_explain.KEY_GET_COPY,
                                             res["size"])
                return data, None
            res = await self.agent.call_retry("fetch_object",
                                              object_id=object_id,
                                              size=res["size"], locations=[],
                                              pin=True,
                                              pinner=self.address)
        # Retries exhausted: the FINAL refetch above may have granted a pin
        # nothing will ever view — release it or the object (and the agent's
        # ledger entry) stays pinned until this whole process exits.
        if res.get("pinned"):
            self.release_read_pin(object_id)
        raise ObjectLostError(object_id)

    def release_read_pin(self, oid: ObjectID):
        """Fire-and-forget ``store_unpin_read`` to our agent (called from
        ``_ReadPin``, possibly on a GC/finalizer thread)."""
        if self._shutdown or self.agent is None:
            return
        try:
            loop = get_loop()
        except Exception:
            return

        async def _send():
            try:
                await self.agent.notify("store_unpin_read", object_id=oid,
                                        pinner=self.address)
            except Exception:
                pass

        try:
            asyncio.run_coroutine_threadsafe(_send(), loop)
        except Exception:
            pass

    async def _try_reconstruct(self, ref: ObjectRef, record: PlasmaRecord):
        """Lineage reconstruction (reference: object_recovery_manager.h:41)."""
        if not get_config().lineage_reconstruction_enabled:
            raise ObjectLostError(ref.id)
        if ref.owner not in ("", self.address):
            owner = self.worker_clients.get(ref.owner)
            # token'd retry: a reconstruct whose reply was lost must not
            # resubmit the producing task a second time
            ok = await owner.call_retry("reconstruct_object",
                                        object_id=ref.id)
            if not ok:
                raise ObjectLostError(ref.id)
            rec = await self._resolve_record(
                ObjectRef(ref.id, owner=ref.owner, _register=False), None)
            if isinstance(rec, PlasmaRecord):
                # owner= so the pull registers this node as a NEW location:
                # without it the owner's view omits post-reconstruction
                # holders and a later loss can't find the live copy
                res = await self.agent.call_retry(
                    "fetch_object", object_id=ref.id, size=rec.size,
                    locations=rec.locations, owner=ref.owner,
                    pin=True, pinner=self.address)
                return await self._read_fetched(ref.id, res)
            raise ObjectLostError(ref.id)
        spec = self.task_manager.lineage.get(ref.id.task_id())
        if spec is None:
            raise ObjectLostError(ref.id)
        self.memory_store.free(ref.id)
        resub = pickle.loads(pickle.dumps(spec))  # fresh copy
        resub.retry_count += 1
        # Re-register as pending so the re-run's results are stored (complete()
        # drops results for unknown tasks).
        self.task_manager.add_pending(resub, [])
        self._submit_spec(resub)
        rec = await self._resolve_record(
            ObjectRef(ref.id, owner=self.address, _register=False), None)
        if isinstance(rec, PlasmaRecord):
            res = await self.agent.call_retry(
                "fetch_object", object_id=ref.id, size=rec.size,
                locations=rec.locations, owner=self.address,
                pin=True, pinner=self.address)
            return await self._read_fetched(ref.id, res)
        if isinstance(rec, ErrorRecord):
            exc, tb = pickle.loads(rec.error)
            raise TaskError(exc, "reconstruction", tb)
        return rec, None  # inline flat bytes — caller deserializes

    # ------------------------------------------------------------------ wait

    def wait(self, refs: List[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        self._on_block()
        try:
            return run_async(self.wait_async(refs, num_returns, timeout))
        finally:
            self._on_unblock()

    async def wait_async(self, refs: List[ObjectRef], num_returns: int,
                         timeout: Optional[float]):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        ready_set: set = set()
        deadline = None if timeout is None else time.monotonic() + timeout

        async def check_one(r: ObjectRef) -> bool:
            if self.memory_store.contains(r.id):
                return True
            if r.owner in ("", self.address):
                return False
            try:
                owner = self.worker_clients.get(r.owner)
                rec = await owner.call_retry("locate_object", object_id=r.id,
                                             timeout=0, _attempts=3,
                                             _idempotent=False)
                if rec is not None:
                    return True
            except Exception:
                return True  # owner dead => resolved (to an error) on get
            return False

        while True:
            for r in refs:
                if r not in ready_set and await check_one(r):
                    ready_set.add(r)
            if len(ready_set) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            await asyncio.sleep(0.005)
        ready = [r for r in refs if r in ready_set][:num_returns]
        ready_ids = set(ready)
        not_ready = [r for r in refs if r not in ready_ids]
        return ready, not_ready

    # ------------------------------------------------------------ submission

    def submit_task(self, spec: TaskSpec, arg_refs: List[ObjectRef]):
        """Fire-and-forget: bookkeeping happens on the calling thread (dict
        ops under the GIL), dispatch hops to the IO loop without waiting for
        it.  Blocking the caller on a cross-thread round trip per submission
        capped async task throughput at ~1k/s (reference: task submission is
        likewise a non-blocking enqueue, direct_task_transport.h:75).

        Returns a list of ObjectRefs, or an ObjectRefGenerator for
        ``num_returns="streaming"`` tasks."""
        self.admission_gate.acquire(self, spec)
        if spec.num_returns == STREAMING_RETURNS:
            self.streams[spec.task_id] = StreamState(
                spec.task_id, spec.generator_backpressure)
            ret = ObjectRefGenerator(self, spec.task_id)
        elif spec.num_returns == 1:
            # dominant case: one return — register against our own counter
            # directly (skips the per-ref global-worker lookup inside
            # _ref_created)
            r = ObjectRef(ObjectID.for_task_return(spec.task_id, 0),
                          self.address, _register=False)
            r._registered = True
            self.reference_counter.add_local_ref(r.id, r.owner)
            ret = [r]
        else:
            ret = [ObjectRef(oid, owner=self.address)
                   for oid in spec.return_ids()]
        self.task_manager.add_pending(spec, arg_refs, gated=True)
        self.task_event(spec, "SUBMITTED")
        self._enqueue_submit(("task", spec))
        return ret

    def _enqueue_submit(self, item: tuple):
        promote = False
        with self._submit_lock:
            self._submit_buffer.append(item)
            need_flush = not self._submit_flush_scheduled
            self._submit_flush_scheduled = True
            if (not need_flush and not self._submit_flush_promoted
                    and len(self._submit_buffer)
                    >= get_config().submit_flush_max):
                # An armed flush window already exists but the buffer hit
                # the size bound: promote to an immediate flush.
                promote = self._submit_flush_promoted = True
        if need_flush:
            get_loop().call_soon_threadsafe(self._arm_submit_flush)
        elif promote:
            get_loop().call_soon_threadsafe(self._flush_submits)

    def _arm_submit_flush(self):
        """On the IO loop: flush now, or arm the bounded flush window
        (``submit_flush_window_ms``) so a burst's stragglers coalesce into
        the same batch.  A window only ever delays by the configured bound;
        ``submit_flush_max`` promotes a full buffer to an immediate flush."""
        cfg = get_config()
        window = (cfg.submit_flush_window_ms
                  if cfg.submit_batching_enabled else 0.0)
        if window > 0 and len(self._submit_buffer) < cfg.submit_flush_max:
            self._submit_timer = asyncio.get_event_loop().call_later(
                window / 1000.0, self._flush_submits)
        else:
            self._flush_submits()

    def _flush_submits(self):
        timer, self._submit_timer = self._submit_timer, None
        if timer is not None:
            timer.cancel()  # no-op when we ARE the timer callback
        om = sched_explain.owner_metrics()
        t0 = time.perf_counter() if om is not None else 0.0
        with self._submit_lock:
            items = list(self._submit_buffer)
            self._submit_buffer.clear()
            self._submit_flush_scheduled = False
            self._submit_flush_promoted = False
        if not items:
            return  # a promoted flush raced the window timer's flush
        pools: Dict[int, LeasePool] = {}
        pumped_actors: Dict[str, ActorTarget] = {}
        for kind, *rest in items:
            if kind == "task":
                (spec,) = rest
                pool = self._pool_for(spec)
                pool.queue.append(spec)
                pools[id(pool)] = pool
            else:  # actor call
                actor_id, spec = rest
                tgt = self.actor_targets.setdefault(actor_id,
                                                    ActorTarget(actor_id))
                tgt.outbox.append(spec)
                pumped_actors[actor_id] = tgt
        for pool in pools.values():
            pool._pump()
        for actor_id, tgt in pumped_actors.items():
            if not tgt.pump_running:
                tgt.pump_running = True
                asyncio.ensure_future(self._actor_pump(actor_id, tgt))
        if om is not None:
            # flush-time attribution: routing + pump work this IO-loop
            # callback spent on the burst (serialization is separate —
            # raytpu_sched_owner_serialize_seconds)
            om["flush"].observe(time.perf_counter() - t0)

    def _pool_for(self, spec: TaskSpec) -> LeasePool:
        bundle = None
        strategy = spec.scheduling_strategy
        if isinstance(strategy, tuple) and strategy and strategy[0] == "_pg":
            bundle = (strategy[1], strategy[2])
            strategy = NodeAffinitySchedulingStrategy(strategy[3], soft=False)
        key = spec.scheduling_key() + ((bundle,) if bundle else ())
        pool = self.lease_pools.get(key)
        if pool is None:
            pool = LeasePool(self, key, spec.resources, strategy, bundle,
                             spec.runtime_env)
            self.lease_pools[key] = pool
        if pool.label is None:
            pool.label = spec.name
        return pool

    def _submit_spec(self, spec: TaskSpec):
        self._pool_for(spec).submit(spec)

    # -------------------------------------------------------------- actors

    def create_actor(self, spec: TaskSpec, get_if_exists: bool = False) -> str:
        return run_async(self._create_actor_async(spec, get_if_exists))

    async def _create_actor_async(self, spec: TaskSpec,
                                  get_if_exists: bool = False) -> str:
        # Exactly-once registration: the idempotency token dedups a retry
        # whose original reply was lost, so a flaky GCS link can never
        # register (and schedule) the same actor twice.
        aid = await self.gcs.call_retry("register_actor", spec=spec,
                                        get_if_exists=get_if_exists)
        self.actor_targets.setdefault(aid, ActorTarget(aid))
        return aid

    def submit_actor_task(self, actor_id: str, spec: TaskSpec,
                          arg_refs: List[ObjectRef]):
        """Fire-and-forget like submit_task: enqueue into the target's
        ordered outbox on the IO loop; the per-target pump batches and
        sends.  Streaming methods return an ObjectRefGenerator."""
        self.admission_gate.acquire(self, spec)
        if spec.num_returns == STREAMING_RETURNS:
            self.streams[spec.task_id] = StreamState(
                spec.task_id, spec.generator_backpressure)
            ret = ObjectRefGenerator(self, spec.task_id)
        elif spec.num_returns == 1:
            # dominant case: one return — register against our own counter
            # directly (skips the per-ref global-worker lookup inside
            # _ref_created)
            r = ObjectRef(ObjectID.for_task_return(spec.task_id, 0),
                          self.address, _register=False)
            r._registered = True
            self.reference_counter.add_local_ref(r.id, r.owner)
            ret = [r]
        else:
            ret = [ObjectRef(oid, owner=self.address)
                   for oid in spec.return_ids()]
        self.task_manager.add_pending(spec, arg_refs, gated=True)
        self.task_event(spec, "SUBMITTED")
        self._enqueue_submit(("actor", actor_id, spec))
        return ret

    async def _actor_pump(self, actor_id: str, tgt: ActorTarget):
        try:
            while tgt.outbox:
                batch: List[TaskSpec] = []
                cfg = get_config()
                limit = (cfg.actor_call_pipeline
                         if cfg.submit_batching_enabled else 1)
                # Intra-batch dependencies are safe: per-call results are
                # streamed back as they land (handle_actor_task_batch).
                while tgt.outbox and len(batch) < limit:
                    batch.append(tgt.outbox.popleft())
                await self._run_actor_batch(actor_id, tgt, batch)
        finally:
            tgt.pump_running = False
            if tgt.outbox:  # raced with a late enqueue during unwinding
                tgt.pump_running = True
                asyncio.ensure_future(self._actor_pump(actor_id, tgt))

    async def _resolve_actor(self, actor_id: str, timeout: float = 120.0) -> ActorTarget:
        tgt = self.actor_targets.setdefault(actor_id, ActorTarget(actor_id))
        if tgt.state == "ALIVE" and tgt.address:
            return tgt
        # Poll in SHORT long-poll chunks under one deadline: a single
        # timeout-length park on the shared GCS connection loses the whole
        # wait whenever any unrelated frame on that link dies (chaos drop,
        # GCS restart) — short chunks bound the loss to one chunk and the
        # loop absorbs transport faults until the deadline.
        deadline = time.monotonic() + timeout
        while True:
            step = min(10.0, max(0.5, deadline - time.monotonic()))
            try:
                info = await self.gcs.call_retry(
                    "wait_actor_alive", actor_id=actor_id, timeout=step,
                    _timeout=step + 10, _idempotent=False)
            except (ConnectionLost, ConnectionError, OSError,
                    asyncio.TimeoutError):
                info = {"state": "TIMEOUT"}  # transport fault: keep waiting
            if info is None or info.get("state") in ("DEAD",):
                # authoritative answer: unknown or dead
                tgt.state = "DEAD"
                raise ActorDiedError(
                    actor_id, f"actor {actor_id[:12]} is dead: "
                              f"{(info or {}).get('death_cause')}")
            if info.get("state") == "TIMEOUT":
                if time.monotonic() >= deadline:
                    raise ActorDiedError(
                        actor_id,
                        f"timed out resolving actor {actor_id[:12]}")
                await asyncio.sleep(0.2)
                continue
            tgt.address = info["address"]
            tgt.state = "ALIVE"
            return tgt

    async def _run_actor_batch(self, actor_id: str, tgt: ActorTarget,
                               specs: List[TaskSpec]):
        """Send a submission-ordered batch of calls in ONE RPC and complete
        each result.  The pump is the sole sender per target, so seq_nos and
        delivery order are preserved without a lock (reference:
        actor_scheduling_queue.h:40 sequencing)."""
        while specs:
            if tgt.state != "ALIVE" or not tgt.address:
                # the calls' dependency is the ACTOR itself — still being
                # placed or restarted; the typed reason makes a hung
                # handle call diagnosable (raytpu explain <actor id> then
                # shows the GCS-side placement trail)
                for s in specs:
                    self.pending_reason(s, PendingReason.WAITING_DEPS,
                                        actor=actor_id[:16])
            try:
                tgt = await self._resolve_actor(actor_id)
            except ActorDiedError as e:
                for s in specs:
                    self.task_manager.fail(s.task_id, e)
                return
            client = self.worker_clients.get(tgt.address)
            for s in specs:
                s.seq_no = tgt.seq = tgt.seq + 1
                self.task_event(s, "RUNNING")
            try:
                # Wire-encode through the spec template cache: the actor
                # METHOD descriptor (actor id, method name, options) interns
                # once per handle; each call ships args + ids.  Connect
                # first so the delivered-set tracks this connection.
                await client.ensure_connected()
                payloads = await self._encode_offloaded(client, specs)
                if (len(specs) == 1
                        and specs[0].num_returns != STREAMING_RETURNS):
                    # Single non-streaming call: token'd retry.  A reply
                    # lost to a transport fault replays the COMMITTED
                    # result from the worker's dedup window — the method
                    # runs exactly once and no actor-task retry budget is
                    # burned.  (Batches can't retry this way: their
                    # results stream as side-channel pushes that a dedup
                    # replay would not re-emit.)
                    results_list = [await client.call_retry(
                        "actor_task", spec=payloads[0],
                        _timeout=86400.0, _attempts=3)]
                else:
                    # Batch RPC even for one call when it streams: only the
                    # batch handler holds the writer yield frames ride on.
                    results_list = await client.call(
                        "actor_task_batch", specs=payloads,
                        _timeout=86400.0)
            except (RpcError, OSError) as e:
                from .chaos import ChaosFault
                from .rpc import TransientServerError
                if (isinstance(e, RemoteError)
                        and isinstance(e.cause, spec_cache.SpecCacheMiss)):
                    # The actor worker evicted a template we thought
                    # delivered; its decode raised before running anything.
                    # Resend with full templates on the next loop pass.
                    for s in specs:
                        self.pending_reason(
                            s, PendingReason.SPEC_CACHE_RESEND,
                            actor=actor_id[:16])
                    spec_cache.SpecEncoder.forget_client(client)
                    continue
                if (isinstance(e, RemoteError)
                        and not isinstance(e.cause, (ChaosFault,
                                                     TransientServerError))):
                    # app-level failure raised by the actor method
                    for s in specs:
                        self.task_manager.fail(s.task_id, e.cause,
                                               e.remote_traceback)
                    return
                # Transport-level failure — ConnectionLost, "client closed"
                # (pool entry force-closed under us), or a chaos-injected
                # fault (retryable by the harness contract, same
                # at-most-once budget as a lost connection).
                tgt.state = "RESTARTING"
                tgt.address = None
                try:
                    info = await self.gcs.call_retry("get_actor_info",
                                                     actor_id=actor_id,
                                                     _idempotent=False)
                except (ConnectionLost, ConnectionError, OSError,
                        asyncio.TimeoutError):
                    # GCS unreachable (blip/restart): don't let the pump
                    # die — treat as maybe-restarting and retry the batch
                    await asyncio.sleep(0.5)
                    continue
                if info is None or info["state"] == "DEAD":
                    cause = (info or {}).get("death_cause")
                    err = ActorDiedError(
                        actor_id,
                        f"actor {actor_id[:12]} died"
                        + (f": {cause}" if cause else ""))
                    for s in specs:
                        self.task_manager.fail(s.task_id, err)
                    return
                retry = []
                for s in specs:
                    rs = self.task_manager.use_retry(s.task_id)
                    if rs is not None:
                        retry.append(rs)
                    else:
                        self.task_manager.fail(
                            s.task_id,
                            ActorDiedError(
                                actor_id,
                                f"actor {actor_id[:12]} died while running "
                                f"{s.name} (set max_task_retries to retry)"))
                specs = retry
                if specs:
                    await asyncio.sleep(max(0.1, _task_retry_delay(
                        max(s.retry_count for s in specs))))
                continue
            for s, results in zip(specs, results_list):
                if results != "__streamed__":  # else completed via push
                    self.task_manager.complete(s.task_id, results)
            return

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        return run_async(self.gcs.call_retry("kill_actor", actor_id=actor_id,
                                             no_restart=no_restart))

    # ----------------------------------------------------------- ref counting

    def on_ref_count_zero(self, oid: ObjectID, owner: str):
        """All owner-side counts (local/submitted/borrowers) hit zero.

        The free happens immediately UNLESS an escrow hold is registered:
        when a producer serializes this ref into a task result, it places an
        acked hold with us BEFORE replying (``_package_returns``), and the
        consumer releases it AFTER registering its borrow
        (``register_contained_borrow``) — so the in-flight hand-off window is
        covered by explicit protocol, not a timing grace (the reference's
        WaitForRefRemoved bookkeeping, ``reference_count.cc``).  Hold expiry
        (``escrow_hold_expiry_s``) only bounds the leak when a consumer dies
        mid-handoff.
        """
        if self._shutdown:
            return
        try:
            loop = get_loop()
        except Exception:
            return
        if get_config().submit_plane_native_enabled:
            # Coalesced doorbell: run_coroutine_threadsafe costs a self-pipe
            # write (~40 µs of syscall on a busy loop) plus a Task per ref.
            # A drain burst of N ref deaths pays for ONE of each.
            with self._free_lock:
                self._free_buffer.append(oid)
                need_wake = not self._free_scheduled
                self._free_scheduled = True
            if need_wake:
                loop.call_soon_threadsafe(self._drain_frees)
            return
        asyncio.run_coroutine_threadsafe(self._free_owned(oid), loop)

    def _drain_frees(self):
        with self._free_lock:
            oids = self._free_buffer
            self._free_buffer = []
            self._free_scheduled = False
        if oids:
            asyncio.ensure_future(self._free_owned_many(oids))

    async def _free_owned_many(self, oids: list):
        for oid in oids:
            await self._free_owned(oid)

    async def handle_worker_killed(self, worker_id: str, address: str,
                                   cause: str):
        """Agent notification: a worker running OUR lease was deliberately
        killed (memory monitor).  Stash the typed cause and force-close our
        client to the dead worker so an in-flight push fails with
        ConnectionLost NOW — prompt typed-OOM delivery that does not
        depend on EOF timing (the lease-return death_cause remains the
        primary source; this is the backstop)."""
        self._kill_causes[worker_id] = cause
        while len(self._kill_causes) > 256:
            self._kill_causes.pop(next(iter(self._kill_causes)))
        try:
            await self.worker_clients.close(address)
        except Exception:
            pass
        return True

    async def handle_add_object_location(self, object_id: ObjectID,
                                         node_id: str, address: str):
        """A node finished pulling our object: record it as a source so later
        pullers fan out over all holders (tree-shaped broadcast; reference:
        ownership-based object directory location updates)."""
        rec = self.memory_store.get_if_exists(object_id)
        if isinstance(rec, PlasmaRecord):
            loc = (node_id, address)
            if loc not in rec.locations:
                rec.locations.append(loc)
        return True

    async def handle_remove_object_location(self, object_id: ObjectID,
                                            node_id: str, address: str):
        """A node dropped its (possibly partial) copy — e.g. a striped pull
        that registered after its first chunk then failed and freed the
        segment.  Without this, the append-only location list would forever
        route pullers at a holder with nothing to serve."""
        rec = self.memory_store.get_if_exists(object_id)
        if isinstance(rec, PlasmaRecord):
            loc = (node_id, address)
            if loc in rec.locations:
                rec.locations.remove(loc)
        return True

    async def handle_escrow_hold(self, object_id: ObjectID, hold_id: str):
        """A producer is about to ship a result containing our object: keep
        it alive until the consumer's release (or expiry)."""
        self._escrow_holds.setdefault(object_id, {})[hold_id] = (
            time.monotonic() + get_config().escrow_hold_expiry_s)
        return True

    def release_local_hold(self, object_id: ObjectID, hold_id: str):
        try:
            loop = get_loop()
        except Exception:
            return
        asyncio.run_coroutine_threadsafe(
            self.handle_escrow_release(object_id, hold_id), loop)

    async def handle_escrow_release(self, object_id: ObjectID, hold_id: str):
        holds = self._escrow_holds.get(object_id)
        if holds is not None:
            holds.pop(hold_id, None)
            if not holds:
                self._escrow_holds.pop(object_id, None)
        await self._free_owned(object_id)  # no-op while refs/holds remain

    def send_borrower_note(self, oid: ObjectID, owner: str, add: bool):
        """Borrower-side: tell the owner we hold / released a copy of its
        object.  ADD notes are acked calls tracked in _pending_notes so
        task execution can flush them before its results ship (see
        flush_borrower_notes); REMOVE notes stay fire-and-forget."""
        if self._shutdown:
            return
        try:
            loop = get_loop()
        except Exception:
            return

        async def _notify():
            try:
                if add:
                    # token'd retry: a double-applied ADD note would leave
                    # a phantom borrower that pins the object forever
                    await self.worker_clients.get(owner).call_retry(
                        "add_borrower_note", object_id=oid, _timeout=30.0)
                else:
                    await self.worker_clients.get(owner).notify(
                        "remove_borrower_note", object_id=oid)
            except Exception:
                pass

        fut = asyncio.run_coroutine_threadsafe(_notify(), loop)
        if add:
            self._pending_notes.add(fut)
            fut.add_done_callback(self._pending_notes.discard)

    def flush_borrower_notes(self, timeout: float = 10.0):
        """Block until every in-flight ADD borrower note is ACKED by its
        owner.  Called at the end of task execution, BEFORE results ship:
        the submitter releases its argument pins the moment it processes
        our results, so the owner must already know about any borrows this
        task registered — otherwise a ref kept by an actor/task could be
        freed in the note-vs-result race (reference: reference_count.cc
        WaitForRefRemoved ordering)."""
        import concurrent.futures
        pending = list(self._pending_notes)
        if pending:
            concurrent.futures.wait(pending, timeout=timeout)

    def register_contained_borrow(self, result_oid: ObjectID, cid: ObjectID,
                                  owner: str, hold_id: Optional[str] = None):
        """A task result we own contains a ref owned elsewhere: hold a borrow
        on it for as long as the result object itself is alive, then release
        the producer's escrow hold — ordered AFTER our borrower note on the
        same connection, so the owner always learns of the borrow before the
        hold drops."""
        self._contained_borrows.setdefault(result_oid, []).append((cid, owner))
        self.reference_counter.add_local_ref(cid, owner)
        if hold_id and owner and owner != self.address:
            try:
                loop = get_loop()
            except Exception:
                return

            async def _release():
                try:
                    await self.worker_clients.get(owner).notify(
                        "escrow_release", object_id=cid, hold_id=hold_id)
                except Exception:
                    pass  # expiry reclaims

            asyncio.run_coroutine_threadsafe(_release(), loop)

    async def _free_owned(self, oid: ObjectID):
        if self.reference_counter.has_any_ref(oid):
            return
        holds = self._escrow_holds.get(oid)
        if holds:
            now = time.monotonic()
            live = {h: d for h, d in holds.items() if d > now}
            if live:
                self._escrow_holds[oid] = live
                # consumer-death safety valve: retry at the earliest expiry
                delay = max(0.05, min(live.values()) - now)
                loop = asyncio.get_event_loop()
                loop.call_later(delay, lambda: asyncio.ensure_future(
                    self._free_owned(oid)))
                return
            self._escrow_holds.pop(oid, None)
        for cid, owner in self._contained_borrows.pop(oid, []):
            self.reference_counter.remove_local_ref(cid, owner)
        rec = self.memory_store.get_if_exists(oid)
        self.memory_store.free(oid)
        if rec is not None and not isinstance(rec, PlasmaRecord):
            # inline record: no store sees this free, stamp it here (the
            # plasma fan-out below is stamped by each store's own FREED)
            self.object_event(oid, ObjectEvent.FREED)
        if isinstance(rec, PlasmaRecord):
            from . import external_spill
            for node_id, addr in rec.locations:
                if external_spill.is_external_address(addr):
                    # external-tier copy: not an agent to RPC — the owner
                    # is its single deletion point (spilling nodes never
                    # delete it; they may already be gone)
                    try:
                        await asyncio.get_event_loop().run_in_executor(
                            None, external_spill.delete, addr)
                    except Exception:
                        pass
                    continue
                try:
                    await self.agent_clients.get(addr).call_retry(
                        "store_free", object_ids=[oid])
                except Exception:
                    pass

    def free(self, refs: List[ObjectRef]):
        async def _free():
            for r in refs:
                await self._free_owned(r.id)
        run_async(_free())

    # ----------------------------------------------------- blocked accounting

    def _on_block(self):
        """Called when user code blocks on get/wait inside a task — tells the
        agent to release the lease's resources so nested tasks can run
        (reference: raylet releases resources for blocked workers,
        ``local_task_manager.h``)."""
        if self.mode != "worker" or self.agent is None:
            return
        self._blocked_depth += 1
        if self._blocked_depth == 1:
            self._notify_agent("worker_blocked")

    def _on_unblock(self):
        if self.mode != "worker" or self.agent is None:
            return
        self._blocked_depth -= 1
        if self._blocked_depth == 0:
            self._notify_agent("worker_unblocked")

    def _notify_agent(self, method: str):
        wid = self.worker_id.hex()

        async def _send():
            try:
                await self.agent.notify(method, worker_id=wid)
            except Exception:
                pass

        try:
            asyncio.run_coroutine_threadsafe(_send(), get_loop())
        except Exception:
            pass

    # =========================================================== RPC handlers

    async def handle_dump_stacks(self) -> str:
        from ray_tpu.util.debug import dump_all_stacks
        return dump_all_stacks()

    async def handle_profile(self, duration_s: float = 2.0,
                             out_dir: str = "/tmp/raytpu/profiles"):
        """On-demand profiler capture (``raytpu profile``): jax.profiler
        when this process runs a non-CPU backend, thread-stack sampling
        to chrome-trace JSON otherwise.  The capture sleeps for the whole
        window, so it runs OFF the RPC loop."""
        from ray_tpu.util import profiler
        loop = asyncio.get_event_loop()
        path, mode = await loop.run_in_executor(
            None, lambda: profiler.capture(duration_s, out_dir))
        return {"path": path, "mode": mode,
                "process": f"worker-{self.worker_id.hex()[:12]}"}

    async def handle_chaos_update(self, spec: Optional[dict] = None):
        """Runtime chaos-spec propagation: the node agent forwards GCS
        chaos_set/chaos_clear broadcasts to every worker it manages."""
        from . import chaos
        chaos.install(spec)
        return True

    async def handle_ping(self):
        return "pong"

    async def handle_owned_object_count(self) -> int:
        """Number of live objects this process owns (idle-reap guard)."""
        return len(self.memory_store)

    async def handle_locate_object(self, object_id: ObjectID, timeout: float = 30.0):
        """Owner-side: return the record for an object, waiting for the producing
        task up to `timeout`. None => not ready yet."""
        if not self.memory_store.contains(object_id):
            ok = await self.memory_store.wait_ready(object_id,
                                                    timeout if timeout else 0.001)
            if not ok:
                return None
        rec = self.memory_store.get_if_exists(object_id)
        if isinstance(rec, PlasmaRecord):
            return ("plasma", rec.size, rec.locations)
        if isinstance(rec, ErrorRecord):
            return ("error", rec.error, rec.system)
        if (isinstance(rec, (bytes, bytearray)) and self.agent is not None
                and len(rec) > get_config().max_direct_call_object_size):
            # A result inlined under inline_result_max_bytes is being
            # borrowed cross-process and exceeds the direct-call size:
            # promote it to the shm store so borrowers ride the transfer
            # plane (chunked pulls, zero-copy same-host) instead of every
            # locate_object reply copying the payload.
            plas = await self._promote_inline(object_id, rec)
            if plas is not None:
                return ("plasma", plas.size, plas.locations)
            rec = self.memory_store.get_if_exists(object_id)
            if rec is None or isinstance(rec, PlasmaRecord):
                return None if rec is None else ("plasma", rec.size,
                                                 rec.locations)
        return ("inline", rec)

    async def _promote_inline(self, oid: ObjectID, data) -> Optional[PlasmaRecord]:
        """Spill one inlined result to the node's shm store (borrower
        appeared).  Deduped per object so concurrent borrowers share a
        single ``store_create``; ownership and refcounts do not move — the
        record simply becomes a PlasmaRecord whose free path is the
        standard ``store_free`` fan-out."""
        fut = self._promotions.get(oid)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_event_loop().create_future()
        self._promotions[oid] = fut
        rec: Optional[PlasmaRecord] = None
        try:
            try:
                res = await self.agent.call_retry("store_create",
                                                  object_id=oid,
                                                  size=len(data),
                                                  owner=self.address)
                # stamped before the seal notify so CREATED can never sort
                # after the agent's SEALED (see _store_serialized)
                object_explain.ledger_record(object_explain.KEY_PROMOTE,
                                             len(data))
                self.object_event(oid, ObjectEvent.CREATED, size=len(data),
                                  node=(self.node_id or "")[:12] or None,
                                  promoted=True)
                seg = ShmSegment(res["path"], len(data), create=False)
                try:
                    seg.view()[:len(data)] = data
                finally:
                    seg.close()
                await self.agent.notify("store_seal", object_id=oid)
            except Exception:
                fut.set_result(None)
                return None
            if not self.memory_store.contains(oid):
                # the last reference died mid-promotion: the inline record
                # is gone, so the shm copy must go too (nobody will free it)
                try:
                    await self.agent.call_retry("store_free",
                                                object_ids=[oid])
                except Exception:
                    pass
                fut.set_result(None)
                return None
            rec = PlasmaRecord(len(data),
                               [(self.node_id, self.agent_address)])
            self.memory_store.put(oid, rec)
            fut.set_result(rec)
            return rec
        finally:
            if not fut.done():
                fut.set_result(rec)
            self._promotions.pop(oid, None)

    async def handle_get_object(self, object_id: ObjectID):
        return await self.handle_locate_object(object_id, timeout=30.0)

    async def handle_reconstruct_object(self, object_id: ObjectID) -> bool:
        spec = self.task_manager.lineage.get(object_id.task_id())
        if spec is None:
            return False
        self.memory_store.free(object_id)
        resub = pickle.loads(pickle.dumps(spec))
        resub.retry_count += 1
        if resub.num_returns == STREAMING_RETURNS:
            live = self.streams.get(resub.task_id)
            if live is not None:
                # A consumer still holds this stream: keep its cursor and
                # let the replay overwrite unconsumed indexes (the task-retry
                # contract) — installing a replay state here would rewind the
                # consumer to index 0 and then vanish mid-iteration.
                live.reset_for_retry()
            else:
                # Consumer long gone; a fresh replay-mode StreamState so
                # _on_gen_yield re-stores every yield (only block refs live).
                st = StreamState(resub.task_id, resub.generator_backpressure)
                st.replay = True
                self.streams[resub.task_id] = st
        self.task_manager.add_pending(resub, [])
        self._submit_spec(resub)
        return True

    async def handle_remove_borrower_note(self, object_id: ObjectID):
        # Owner-side escrow: apply the removal only after the grace window, so
        # a ref the borrower *forwarded* (task result / actor reply) has time
        # to be re-registered by the receiver's add note.  Processing the
        # delay here (not at the sender) means a borrower exiting right after
        # sending cannot lose the note.
        await asyncio.sleep(get_config().ref_escrow_grace_s)
        self.reference_counter.remove_borrower(object_id)

    async def handle_add_borrower_note(self, object_id: ObjectID):
        self.reference_counter.add_borrower(object_id)

    # -- execution (worker mode) ------------------------------------------

    async def handle_push_task(self, spec):
        spec = spec_cache.decode(spec)
        fut = asyncio.get_event_loop().create_future()
        self.exec_queue.put(("task", spec, fut, asyncio.get_event_loop()))
        return await fut

    def register_gen_emitter(self, spec: TaskSpec, writer, loop):
        """Executor side: wire a streaming task to the live batch connection
        before it runs (called from the batch RPC handlers, on the IO loop)."""
        if spec.num_returns == STREAMING_RETURNS and writer is not None:
            self._gen_emitters[spec.task_id] = _GenEmitter(writer, loop)

    async def handle_generator_ack(self, task_id: TaskID, consumed: int):
        """Backpressure credit from the consuming owner (one-way notify)."""
        em = self._gen_emitters.get(task_id)
        if em is not None:
            em.ack(consumed)

    def _make_result_streamer(self, writer, task_id: TaskID):
        """Done-callback that pushes one task's results to the submitter the
        moment it finishes (req_id -1 frame on the batch connection).  This
        is what makes batching deadlock-free: a consumer later in the batch
        (or holding the producer's ref indirectly) can resolve it at the
        owner without waiting for the whole batch to reply.

        Results completing in the same loop tick COALESCE into one
        ``task_result_batch`` push frame (one pickle + one frame per tick
        instead of per task) — the per-result frame overhead was one of
        the measured owner/worker-loop ceilings on big drains."""
        from .rpc import _encode, coalesced_write

        def _flush():
            buf = getattr(writer, "_raytpu_result_buf", None)
            writer._raytpu_result_buf = None
            if not buf:
                return
            try:
                # Same coalescing as the reply path: every frame on this
                # writer must queue through coalesced_write or interleaved
                # direct writes would reorder against buffered ones.
                coalesced_write(writer, _encode(
                    (-1, "task_result_batch", {"results": buf})))
            except Exception:
                pass  # connection gone: the batch reply path handles it

        def _cb(fut):
            # A streaming task that failed before its generator body ran
            # never reaches _run_generator's finally: drop its emitter here
            # (the one chokepoint every batch-dispatched task passes).
            self._gen_emitters.pop(task_id, None)
            try:
                results = fut.result()
            except Exception:
                return
            if not get_config().completion_batching_enabled:
                # A/B off arm: one push frame per result, as before
                try:
                    coalesced_write(writer, _encode(
                        (-1, "task_result",
                         {"task_id": task_id, "results": results})))
                except Exception:
                    pass
                return
            buf = getattr(writer, "_raytpu_result_buf", None)
            if buf is None:
                buf = writer._raytpu_result_buf = []
                try:
                    asyncio.get_event_loop().call_soon(_flush)
                except RuntimeError:
                    writer._raytpu_result_buf = None
                    try:
                        coalesced_write(writer, _encode(
                            (-1, "task_result",
                             {"task_id": task_id, "results": results})))
                    except Exception:
                        pass
                    return
            buf.append((task_id, results))

        return _cb

    def _on_peer_push_routed(self, topic: str, payload: dict):
        """Push-handler shim for laned connections: completion bookkeeping
        (task manager, memory store, streams) is lane-0-confined state, so
        pushes arriving on a submission lane's read loop hop home first.
        call_soon_threadsafe is FIFO per calling thread, and a connection
        lives wholly on one lane — per-connection ordering (yield index
        order, yields-before-final-result) is preserved."""
        loop0 = get_loop()
        try:
            on_home = asyncio.get_running_loop() is loop0
        except RuntimeError:
            on_home = False
        if on_home:
            self._on_peer_push(topic, payload)
        else:
            loop0.call_soon_threadsafe(self._on_peer_push, topic, payload)

    def _on_peer_push(self, topic: str, payload: dict):
        if topic == "task_result":
            self.task_manager.complete(payload["task_id"],
                                       payload["results"])
        elif topic == "task_result_batch":
            # one admission-gate release for the whole batch (the gate's
            # lock/notify per completion was measurable at drain rates)
            self.task_manager.complete_many(payload["results"])
        elif topic == "gen_yield":
            self._on_gen_yield(payload["task_id"], payload["index"],
                               payload["result"], payload["worker"])

    def _on_gen_yield(self, task_id: TaskID, index: int, res: tuple,
                      worker_addr: str):
        """Owner side: one yield arrived from a running streaming task.
        Yields arrive in index order on the TCP stream (and before the final
        task_result frame)."""
        st = self.streams.get(task_id)
        if st is None or st.abandoned:
            return  # generator dropped: let the value die with the producer
        oid = ObjectID.for_task_return(task_id, index)
        self.store_task_result(oid, res)
        self.task_manager.register_result_borrows(oid, res)
        if res[0] == "plasma":
            st.any_plasma = True
        st.worker_addr = worker_addr
        st.available = index + 1
        if st.backpressure and worker_addr and index < st.next_read:
            # Replay of an already-consumed index (task retry): the consumer
            # won't call next() until production passes its cursor, so ack
            # proactively — otherwise the fresh producer parks at the
            # backpressure window with nobody left to drain it.
            try:
                client = self.worker_clients.get(worker_addr)
                asyncio.ensure_future(client.notify(
                    "generator_ack", task_id=task_id,
                    consumed=st.next_read))
            except Exception:
                pass
        st.signal()

    async def handle_push_task_batch(self, specs: List[TaskSpec],
                                     _writer=None):
        """Batched push: N tasks in one RPC, executed in submission order on
        the main thread, each result STREAMED back as it lands, one final
        reply as the completion barrier (reference counterpart:
        direct_task_transport.h:151 pipelining)."""
        # Template decode is all-or-nothing: a SpecCacheMiss raises BEFORE
        # any task is queued, so the sender's resend re-runs nothing.
        specs = spec_cache.decode_many(specs)
        loop = asyncio.get_event_loop()
        futs = []
        for spec in specs:
            fut = loop.create_future()
            if _writer is not None:
                fut.add_done_callback(
                    self._make_result_streamer(_writer, spec.task_id))
            self.register_gen_emitter(spec, _writer, loop)
            self.exec_queue.put(("task", spec, fut, loop))
            futs.append(fut)
        results = await asyncio.gather(*futs)
        if _writer is not None:
            # Results already streamed (and processed in-order before this
            # reply); don't pickle them all a second time.
            return ["__streamed__"] * len(results)
        return results

    handle_push_task_batch.rpc_pass_writer = True

    async def handle_actor_task_batch(self, specs: List[TaskSpec],
                                      _writer=None):
        """Batched ordered actor calls with the same per-call result
        streaming.  Async actors overlap the whole batch on their private
        loop; threaded actors keep per-call dispatch so the batch doesn't
        defeat max_concurrency."""
        specs = spec_cache.decode_many(specs)  # raises before any dispatch
        loop = asyncio.get_event_loop()
        futs = []
        for spec in specs:
            self.register_gen_emitter(spec, _writer, loop)
            if self.actor_spec is not None and self.actor_spec.is_async_actor:
                fut = asyncio.ensure_future(self._run_async_actor_task(spec))
            else:
                fut = loop.create_future()
                self.exec_queue.put(("task", spec, fut, loop))
            if _writer is not None:
                fut.add_done_callback(
                    self._make_result_streamer(_writer, spec.task_id))
            futs.append(fut)
        results = list(await asyncio.gather(*futs))
        if _writer is not None:
            return ["__streamed__"] * len(results)
        return results

    handle_actor_task_batch.rpc_pass_writer = True

    async def handle_create_actor(self, spec: TaskSpec):
        fut = asyncio.get_event_loop().create_future()
        self.exec_queue.put(("create_actor", spec, fut, asyncio.get_event_loop()))
        return await fut

    async def handle_actor_task(self, spec):
        spec = spec_cache.decode(spec)
        if self.actor_spec is not None and self.actor_spec.is_async_actor:
            return await self._run_async_actor_task(spec)
        fut = asyncio.get_event_loop().create_future()
        self.exec_queue.put(("task", spec, fut, asyncio.get_event_loop()))
        return await fut

    async def handle_exit_worker(self):
        self.exec_queue.put(("exit", None, None, None))
        return True

    # -- executor loop (runs on the worker's MAIN thread) ------------------

    def run_executor_loop(self):
        """Main loop of a worker process: execute tasks from the queue.

        Runs user code on the main thread so jax/TPU state is thread-stable.
        Threaded actors (max_concurrency>1) fan out to a bounded pool
        (reference: BoundedExecutor, thread_pool.h:36).
        """
        while not self._shutdown:
            try:
                item = self.exec_queue.get(timeout=0.5)
            except _queue.Empty:
                continue
            kind, spec, fut, loop = item
            if kind == "exit":
                break
            if (kind == "task" and self.actor_instance is not None
                    and self.actor_spec.max_concurrency > 1):
                self._actor_threadpool.submit(self._execute_and_reply, spec, fut, loop)
            else:
                self._execute_and_reply(spec, fut, loop)

    def _execute_one(self, spec: TaskSpec) -> List[tuple]:
        try:
            if spec.is_actor_creation:
                return self._execute_actor_creation(spec)
            return self._execute_task(spec)
        except BaseException as e:  # noqa: BLE001
            from .actor import ActorExitRequest
            if isinstance(e, ActorExitRequest) and spec.is_actor_task:
                # exit_actor(): intended termination — pre-report the
                # expected death (GCS marks DEAD, no restart burn), answer
                # the in-flight call with a typed intended-exit error, and
                # leave the process once the reply flushes.
                self._begin_intended_exit(spec)
                err = ActorDiedError(
                    spec.actor_id.hex(),
                    f"actor {spec.actor_id.hex()[:12]} exited via "
                    "exit_actor() (intended)")
                return [("error", pickle.dumps((err, "")), True)
                        for _ in range(max(1, spec.num_returns))]
            tb = traceback.format_exc()
            return [("error", pickle.dumps((_strip_exc(e), tb)))
                    for _ in range(max(1, spec.num_returns))]

    def _begin_intended_exit(self, spec: TaskSpec):
        # Mark the exit intended at BOTH authorities: the agent flag makes
        # the process-exit backstop report expected=True (so a lost GCS
        # report cannot burn a restart), the direct GCS report makes the
        # death visible before the process is even gone.
        try:
            run_async(self.agent.call_retry("worker_intended_exit",
                                            worker_id=self.worker_id.hex(),
                                            _timeout=4), timeout=5)
        except Exception:
            pass
        try:
            run_async(self.gcs.call_retry(
                "report_actor_death", actor_id=spec.actor_id.hex(),
                reason="exit_actor() (intended)", expected=True,
                _timeout=8), timeout=10)
        except Exception:
            pass
        # Exit AFTER the typed reply has had time to flush.  Timers must be
        # armed from the loop thread (call_later off-thread races the
        # selector); 2s covers a loaded box's coalesced-write backlog, and
        # a dropped reply still surfaces typed via the caller's
        # ConnectionLost -> GCS death_cause fallback.
        loop = get_loop()
        loop.call_soon_threadsafe(lambda: loop.call_later(2.0, os._exit, 0))

    def _execute_and_reply(self, spec: TaskSpec, fut, loop):
        results = self._execute_one(spec)
        if get_config().submit_plane_native_enabled:
            # Coalesced reply doorbell: a burst of completions wakes the
            # worker's IO loop once, not once per task (each
            # call_soon_threadsafe costs a self-pipe write).
            with self._reply_lock:
                self._reply_buffer.append((fut, results))
                need_wake = not self._reply_scheduled
                self._reply_scheduled = True
            if need_wake:
                loop.call_soon_threadsafe(self._drain_replies)
            return
        loop.call_soon_threadsafe(
            lambda: fut.set_result(results) if not fut.done() else None)

    def _drain_replies(self):
        with self._reply_lock:
            pairs = self._reply_buffer
            self._reply_buffer = []
            self._reply_scheduled = False
        for fut, results in pairs:
            if not fut.done():
                fut.set_result(results)

    def _load_function(self, fn_id: bytes, job_id=None):
        if job_id is not None:
            # Materialize the job's runtime env (py_modules on sys.path, env
            # vars) BEFORE the function runs — unconditionally, not on cache
            # miss: fn_id is a content hash shared across jobs, so job B's
            # env must apply even when job A already cached the function.
            # ensure() is a set lookup after the first success.  Failures
            # FAIL the task (it would otherwise run with a missing env and
            # die with an unrelated-looking ImportError); the next attempt
            # retries materialization.
            from . import runtime_env
            try:
                runtime_env.ensure(self, job_id.hex())
            except Exception as e:
                raise RuntimeError(
                    f"runtime env materialization failed for job "
                    f"{job_id.hex()[:12]}: {e!r}") from e
        fn = self.fn_cache.get(fn_id)
        if fn is None:
            blob = run_async(self.gcs.call_retry(
                "kv_get", ns="funcs", key=fn_id.hex(), _idempotent=False))
            if blob is None:
                raise RuntimeError(f"function {fn_id.hex()[:12]} not found in registry")
            fn = serialization.loads_function(blob)
            self.fn_cache[fn_id] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec,
                      stages: Optional[Dict[str, list]] = None):
        global _EMPTY_ARGS_BLOB
        if _EMPTY_ARGS_BLOB is None:
            from .remote_function import serialize_args
            _EMPTY_ARGS_BLOB = serialize_args((), {})[0]
        if spec.args == _EMPTY_ARGS_BLOB:  # canonical empty blob
            if stages is not None:
                now = time.time()
                stages["arg_deser"] = [now, now]
                stages["dep_fetch"] = [now, now]
            return [], {}
        t0 = time.time()
        so = serialization.SerializedObject.from_buffer(spec.args)
        args, kwargs = serialization.deserialize(so)
        t1 = time.time()

        def resolve(x):
            if isinstance(x, _TopLevelRef):
                return self.get(x.ref)
            return x

        out = ([resolve(a) for a in args],
               {k: resolve(v) for k, v in kwargs.items()})
        if stages is not None:
            stages["arg_deser"] = [t0, t1]
            stages["dep_fetch"] = [t1, time.time()]
        return out

    def _execute_task(self, spec: TaskSpec):
        if spec.is_actor_task:
            if self.actor_instance is None:
                raise RuntimeError("actor task on a non-actor worker")
            method = getattr(self.actor_instance, spec.actor_method)
            fn = method
        else:
            fn = self._load_function(spec.fn_id, spec.job_id)
        stages: Dict[str, list] = {}
        args, kwargs = self._resolve_args(spec, stages)
        ctx = {"task_id": spec.task_id, "job_id": spec.job_id,
               "actor_id": spec.actor_id, "name": spec.name}
        if spec.resources:
            # actor METHOD specs carry no resources — leaving the key out
            # lets get_assigned_resources fall through to the actor's
            # creation spec instead of reporting a bogus default
            ctx["resources"] = dict(spec.resources)
        token = _task_context.set(ctx)
        # Execution joins the submitter's trace: spans opened by the task and
        # any remote calls it makes chain under the task's span id.
        trace_id = (spec.trace_ctx[0] if spec.trace_ctx
                    else spec.task_id.hex()[:12])
        trace_token = _tracing.set_context((trace_id,
                                            spec.task_id.hex()[:12]))
        t_exec = time.time()
        try:
            out = fn(*args, **kwargs)
        finally:
            _tracing.reset_context(trace_token)
            _task_context.reset(token)
        t_put = time.time()
        stages["execute"] = [t_exec, t_put]
        results = self._package_returns(spec, out)
        stages["result_put"] = [t_put, time.time()]
        # Borrow notes for refs this task deserialized (and may retain, e.g.
        # actor state) must be ACKED before the results ship — the submitter
        # drops its argument pins as soon as it processes them.
        self.flush_borrower_notes()
        self._record_stages(spec, stages)
        return results

    def _package_returns(self, spec: TaskSpec, out) -> List[tuple]:
        if spec.num_returns == STREAMING_RETURNS:
            return self._run_generator(spec, out)
        n = spec.num_returns
        values = [out] if n == 1 else list(out) if n > 1 else []
        if n > 1 and len(values) != n:
            raise ValueError(f"task {spec.name} declared num_returns={n} but "
                             f"returned {len(values)} values")
        limit = get_config().inline_result_max_bytes
        return [self._package_one(spec, v, i, limit)
                for i, v in enumerate(values)]

    def _package_one(self, spec: TaskSpec, v, index: int,
                     inline_limit: Optional[int] = None) -> tuple:
        """Package one return/yield value as a result descriptor tuple.

        ``inline_limit`` is the result-inlining threshold: task/actor
        returns use ``inline_result_max_bytes`` (values at or under it ride
        back inside the reply frame — no ``store_create``, no caller-side
        fetch), while streaming-generator yields pass the plain
        ``max_direct_call_object_size`` so the yield pipeline bypasses the
        result-inlining knob unchanged."""
        cfg = get_config()
        if inline_limit is None:
            inline_limit = cfg.max_direct_call_object_size
        if v is None and inline_limit > 0:
            # ubiquitous for side-effect calls: skip the pickler
            return ("inline", serialization.none_bytes(), [])
        if cfg.zero_copy_put_enabled and self.agent is not None:
            bounds = serialization.estimate_flat_size(v)
            # floor comparison: an at-threshold value must still inline
            # (the reservation estimate is an upper bound)
            if bounds is not None and bounds[1] > max(
                    inline_limit, cfg.max_direct_call_object_size):
                desc = self._zero_copy_result(spec, v, index, bounds[0])
                if desc is not None:
                    return desc
        so = serialization.serialize(v)
        contained = self._escrow_contained(so.contained_refs)
        size = so.flat_size()
        if size <= inline_limit or self.agent is None:
            return ("inline", so.to_bytes(), contained)
        oid = ObjectID.for_task_return(spec.task_id, index)
        res = run_async(self.agent.call_retry("store_create", object_id=oid,
                                              size=size,
                                              owner=spec.owner or None))
        # A task result landing in plasma is the same serialize-into-arena
        # 1-copy write as a put — it must account the same ledger path and
        # stamp CREATED, or result-heavy workloads (the common case)
        # vanish from the copy-amplification gauge.
        object_explain.ledger_record(object_explain.KEY_PUT, size)
        self.object_event(oid, ObjectEvent.CREATED, size=size,
                          node=(self.node_id or "")[:12] or None,
                          task=spec.task_id.hex()[:16])
        seg = ShmSegment(res["path"], size, create=False)
        try:
            so.write_into(seg.view())
        finally:
            seg.close()
        run_async(self.agent.notify("store_seal", object_id=oid))
        return ("plasma", size,
                [(self.node_id, self.agent_address)], contained)

    def _escrow_contained(self, contained_refs) -> list:
        """Ship descriptors of any ObjectRefs inside a result value so the
        caller can register its borrows at receipt (see
        TaskManager.complete).  For refs owned ELSEWHERE, place an ACKED
        escrow hold with the owner before the result ships: our own
        counts may hit zero right after the reply, and the hold keeps the
        object alive until the consumer registers its borrow and releases
        (no timing window; reference: reference_count.cc
        WaitForRefRemoved)."""
        contained = []
        for r in contained_refs:
            r_owner = r.owner or self.address
            hold_id = f"{self.worker_id.hex()[:12]}:{next(self._hold_seq)}"
            if r_owner == self.address:
                # We own it: hold locally — our last local ref may die
                # the moment this function returns, and the consumer's
                # borrow note is still in flight.
                self._escrow_holds.setdefault(r.id, {})[hold_id] = (
                    time.monotonic()
                    + get_config().escrow_hold_expiry_s)
            else:
                try:
                    run_async(self.worker_clients.get(r_owner).call_retry(
                        "escrow_hold", object_id=r.id, hold_id=hold_id))
                except Exception:
                    hold_id = None  # owner gone: nothing to protect
            contained.append((r.id.binary(), r_owner, hold_id))
        return contained

    def _zero_copy_result(self, spec: TaskSpec, v, index: int,
                          est: int) -> Optional[tuple]:
        """Reserve-then-write landing of one large task result — the same
        zero-copy put pipeline as ``_try_zero_copy_put``, executor-side
        (sync thread, RPCs via run_async).  Returns the plasma descriptor,
        or None on a size-estimate miss (the reservation is released and
        the caller falls back to the classic serialize-then-copy path)."""
        oid = ObjectID.for_task_return(spec.task_id, index)
        res = run_async(self.agent.call_retry("store_create", object_id=oid,
                                              size=est,
                                              owner=spec.owner or None))
        seg = ShmSegment(res["path"], est, create=False)
        try:
            landed = serialization.serialize_into(v, seg.view())
        finally:
            seg.close()
        if landed is None:
            try:
                run_async(self.agent.call_retry("store_free",
                                                object_ids=[oid]))
            except Exception:
                pass
            return None
        contained = self._escrow_contained(landed.contained_refs)
        object_explain.ledger_record(object_explain.KEY_PUT_ZC, landed.used)
        self.object_event(oid, ObjectEvent.CREATED, size=landed.used,
                          node=(self.node_id or "")[:12] or None,
                          task=spec.task_id.hex()[:16], zero_copy=True)
        # seal-truncate to the exact bytes written (see _try_zero_copy_put)
        run_async(self.agent.notify("store_seal", object_id=oid,
                                    size=landed.used))
        return ("plasma", landed.used,
                [(self.node_id, self.agent_address)], contained)

    def _run_generator(self, spec: TaskSpec, out) -> List[tuple]:
        """Drive a streaming task's generator body: package each yield and
        ship it immediately through the batch connection's push channel
        (reference: _raylet.pyx:267 streaming generator protocol).

        Runs on the executor thread.  With no emitter (a dispatch path that
        has no live writer, e.g. spillback push), yields buffer and ship in
        the final reply instead — correct, just not streaming."""
        emitter = self._gen_emitters.get(spec.task_id)
        buffered: List[tuple] = []
        n = 0
        try:
            for v in iter(out) if not hasattr(out, "__next__") else out:
                res = self._package_one(spec, v, n)
                # Borrow notes for refs inside this yield must be acked
                # before it ships (same invariant as whole-task results).
                self.flush_borrower_notes()
                if emitter is not None:
                    emitter.wait_capacity(spec.generator_backpressure)
                    emitter.send(spec.task_id, n, res, self.address)
                else:
                    buffered.append(res)
                n += 1
        finally:
            self._gen_emitters.pop(spec.task_id, None)
        if emitter is None:
            return [("gen_buffered", buffered)]
        return [("gen_done", n)]

    async def _run_generator_async(self, spec: TaskSpec, gen) -> List[tuple]:
        """Async-actor variant of _run_generator: drives an async OR sync
        generator on the actor's private loop (Serve token streaming runs
        through here).  Sync generators still execute their body inline, but
        the backpressure wait is awaitable so only this task parks."""
        emitter = self._gen_emitters.get(spec.task_id)
        buffered: List[tuple] = []
        n = 0

        async def _aiter(g):
            if hasattr(g, "__anext__"):
                async for v in g:
                    yield v
            else:
                for v in iter(g):
                    yield v
                    await asyncio.sleep(0)  # keep the actor loop responsive

        try:
            async for v in _aiter(gen):
                res = self._package_one(spec, v, n)
                self.flush_borrower_notes()
                if emitter is not None:
                    await emitter.wait_capacity_async(spec.generator_backpressure)
                    emitter.send(spec.task_id, n, res, self.address)
                else:
                    buffered.append(res)
                n += 1
        finally:
            self._gen_emitters.pop(spec.task_id, None)
        if emitter is None:
            return [("gen_buffered", buffered)]
        return [("gen_done", n)]

    def _execute_actor_creation(self, spec: TaskSpec):
        cls = self._load_function(spec.fn_id, spec.job_id)
        args, kwargs = self._resolve_args(spec)
        ctx = {"task_id": spec.task_id, "job_id": spec.job_id,
               "actor_id": spec.actor_id, "name": spec.name}
        if spec.resources:
            # actor METHOD specs carry no resources — leaving the key out
            # lets get_assigned_resources fall through to the actor's
            # creation spec instead of reporting a bogus default
            ctx["resources"] = dict(spec.resources)
        token = _task_context.set(ctx)
        try:
            self.actor_instance = cls(*args, **kwargs)
        finally:
            _task_context.reset(token)
        self.actor_spec = spec
        if spec.max_concurrency > 1 and not spec.is_async_actor:
            from concurrent.futures import ThreadPoolExecutor
            self._actor_threadpool = ThreadPoolExecutor(spec.max_concurrency)
        if spec.is_async_actor:
            self._actor_async_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._actor_async_loop.run_forever,
                                 name="actor-async", daemon=True)
            t.start()
        return [("inline", serialization.dumps(None))]

    async def _run_async_actor_task(self, spec: TaskSpec):
        """Async actors: run the coroutine on the actor's private loop with up to
        max_concurrency concurrent tasks (reference: fiber/asyncio actors).

        Arg resolution and result packaging must happen on the actor loop's
        thread too — they block on IO-loop round-trips (run_async), which would
        deadlock if done here on the IO loop thread itself."""

        async def runner():
            # getattr inside the per-spec error scope: a missing method must
            # fail only ITS call, not every call batched with it.
            method = getattr(self.actor_instance, spec.actor_method)
            stages: Dict[str, list] = {}
            args, kwargs = self._resolve_args(spec, stages)
            # Async actor methods join the submitter's trace exactly like
            # sync task execution (_execute_task): spans opened inside the
            # method — a serve replica's batch_wait/prefill/decode stamps —
            # chain under this task's span id, keeping a proxied request
            # ONE connected trace across processes.
            trace_id = (spec.trace_ctx[0] if spec.trace_ctx
                        else spec.task_id.hex()[:12])
            trace_token = _tracing.set_context((trace_id,
                                                spec.task_id.hex()[:12]))
            try:
                t_exec = time.time()
                res = method(*args, **kwargs)
                if asyncio.iscoroutine(res):
                    res = await res
                if spec.num_returns == STREAMING_RETURNS:
                    # Sync generators route through the async driver too —
                    # its backpressure wait is awaitable, so a slow consumer
                    # parks only this task, not the actor's whole event loop.
                    return await self._run_generator_async(spec, res)
                t_put = time.time()
                stages["execute"] = [t_exec, t_put]
                results = self._package_returns(spec, res)
            finally:
                _tracing.reset_context(trace_token)
            stages["result_put"] = [t_put, time.time()]
            self.flush_borrower_notes()  # see _execute_task
            self._record_stages(spec, stages)
            return results

        cfut = asyncio.run_coroutine_threadsafe(runner(), self._actor_async_loop)
        try:
            return await asyncio.wrap_future(cfut)
        except BaseException as e:  # noqa: BLE001
            tb = traceback.format_exc()
            return [("error", pickle.dumps((_strip_exc(e), tb)))
                    for _ in range(max(1, spec.num_returns))]


class _GenEmitter:
    """Executor-side channel for one RUNNING streaming task.

    ``send`` hops yield frames onto the IO loop for the owner's batch
    connection (same req_id -1 push channel as per-task result streaming, so
    yields and the final task_result frame share the TCP stream's ordering).
    ``wait_capacity``/``ack`` implement consumer-driven backpressure: the
    executor thread parks once `produced - consumed` hits the spec's limit and
    the owner's generator_ack notifies it forward."""

    #: give up waiting for acks after this long (owner died / dropped the
    #: generator mid-stream) — proceeding just buffers, it can't corrupt.
    STALL_TIMEOUT_S = 600.0

    def __init__(self, writer, loop):
        self._writer = writer
        self._loop = loop
        self._produced = 0
        self._consumed = 0
        self._cond = threading.Condition()

    def send(self, task_id: TaskID, index: int, res: tuple, worker_addr: str):
        from .rpc import _encode, coalesced_write
        frame = _encode((-1, "gen_yield", {
            "task_id": task_id, "index": index, "result": res,
            "worker": worker_addr}))

        def _write():
            try:
                coalesced_write(self._writer, frame)
            except Exception:
                pass  # connection gone: the batch reply path handles it

        self._loop.call_soon_threadsafe(_write)
        with self._cond:
            self._produced = index + 1

    def ack(self, consumed: int):
        with self._cond:
            self._consumed = max(self._consumed, consumed)
            self._cond.notify_all()

    def wait_capacity(self, backpressure: int):
        if not backpressure:
            return
        deadline = time.monotonic() + self.STALL_TIMEOUT_S
        with self._cond:
            while (self._produced - self._consumed >= backpressure
                   and time.monotonic() < deadline):
                self._cond.wait(timeout=1.0)

    async def wait_capacity_async(self, backpressure: int):
        """Async-actor variant: park in a thread so the actor loop stays live."""
        if not backpressure:
            return
        if self._produced - self._consumed < backpressure:
            return
        await asyncio.get_event_loop().run_in_executor(
            None, self.wait_capacity, backpressure)


def _strip_exc(e: BaseException) -> BaseException:
    """Make an exception picklable by dropping unpicklable attributes."""
    try:
        pickle.dumps(e)
        return e
    except Exception:
        return RuntimeError(f"{type(e).__name__}: {e}")
