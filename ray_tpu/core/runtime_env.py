"""Runtime environments: shipping code + env vars to every worker.

Reference: ``python/ray/_private/runtime_env/`` — the agent materializes
per-job environments (working_dir/py_modules packaged through the GCS,
``packaging.py``; agent ``runtime_env_agent.py:159``).  Scope here: the
job-level environment — ``py_modules`` directories and ``env_vars`` packed
at ``ray_tpu.init(runtime_env=...)`` into the GCS KV; every worker
materializes them once per job before executing that job's first task, so
multi-node deployments distribute real packages, not just cloudpickle
closures — plus the pip-venv, conda, and container isolation plugins
(workers pooled per env hash, launched under the env's interpreter or
inside ``podman run``).
"""

from __future__ import annotations

import io
import json
import os
import sys
import tarfile
from typing import Any, Dict, List, Optional

import cloudpickle

NS = "runtime_envs"


def _pack_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for root, _dirs, files in os.walk(path):
            for fn in files:
                if fn.endswith((".pyc", ".so.tmp")) or "__pycache__" in root:
                    continue
                full = os.path.join(root, fn)
                tf.add(full, arcname=os.path.relpath(full, path))
    return buf.getvalue()


def validate(runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    known = {"py_modules", "env_vars", "working_dir", "pip", "pip_args",
             "container", "conda"}
    unknown = set(runtime_env) - known
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)} "
                         f"(supported: {sorted(known)})")
    pip = runtime_env.get("pip")
    if pip is not None and not (
            isinstance(pip, str)
            or (isinstance(pip, (list, tuple))
                and all(isinstance(p, str) for p in pip))):
        raise ValueError(
            "runtime_env['pip'] must be a list of requirement strings or a "
            f"requirements-file path, got {type(pip).__name__}")
    conda = runtime_env.get("conda")
    if conda is not None:
        if not (isinstance(conda, str)
                or (isinstance(conda, dict) and "dependencies" in conda)):
            raise ValueError(
                "runtime_env['conda'] must be an existing env name (str) "
                "or an environment spec dict with a 'dependencies' list "
                f"(reference conda.py), got {type(conda).__name__}")
        if "pip" in runtime_env:
            # reference: conda.py raises on conda+pip; pip deps belong in
            # the conda spec's dependencies themselves
            raise ValueError(
                "conda and pip runtime envs cannot be combined; put pip "
                "packages inside the conda spec's dependencies")
    container = runtime_env.get("container")
    if container is not None:
        if not isinstance(container, dict) or "image" not in container:
            raise ValueError(
                "runtime_env['container'] must be a dict with at least an "
                "'image' key, e.g. {'image': 'python:3.12', "
                "'run_options': ['--gpus=all']}")
        if container.get("run_options") is not None and not (
                isinstance(container["run_options"], (list, tuple))
                and all(isinstance(o, str)
                        for o in container["run_options"])):
            raise ValueError("container['run_options'] must be a list "
                             "of strings")
        ev = container.get("env_vars")
        if ev is not None and not (
                isinstance(ev, dict)
                and all(isinstance(k, str) and isinstance(v, str)
                        for k, v in ev.items())):
            raise ValueError("container['env_vars'] must be a dict of "
                             "str -> str")
        if "pip" in runtime_env:
            raise ValueError("container and pip runtime envs cannot be "
                             "combined: bake the packages into the image")
    return runtime_env


# ---------------------------------------------------------------------------
# pip/venv isolation (reference: _private/runtime_env/pip.py + uri_cache.py)
# ---------------------------------------------------------------------------

def pip_env_hash(runtime_env: Optional[Dict[str, Any]]) -> Optional[str]:
    """Cache key for a pip environment, or None when the env needs no
    dedicated interpreter.  Workers are pooled per hash: tasks with the same
    pip spec share venv workers; different specs never share a process."""
    if not runtime_env or not runtime_env.get("pip"):
        return None
    import hashlib
    pip = runtime_env["pip"]
    # string form = requirements-file path (Ray-compatible); list = reqs
    spec = (pip if isinstance(pip, str) else sorted(pip),
            list(runtime_env.get("pip_args") or []))
    return hashlib.sha1(repr(spec).encode()).hexdigest()[:16]


def conda_env_hash(runtime_env: Optional[Dict[str, Any]]) -> Optional[str]:
    """Cache/pool key for a conda environment (reference:
    conda.py get_conda_env_name — content hash of the spec)."""
    if not runtime_env or not runtime_env.get("conda"):
        return None
    import hashlib
    conda = runtime_env["conda"]
    spec = conda if isinstance(conda, str) else json.dumps(conda,
                                                           sort_keys=True)
    return hashlib.sha1(repr(spec).encode()).hexdigest()[:16]


def worker_env_hash(runtime_env: Optional[Dict[str, Any]]) -> Optional[str]:
    """Pool key for worker processes: tasks share an idle worker only when
    their isolation spec (pip venv / conda env AND/OR container) is
    identical."""
    parts = []
    h = pip_env_hash(runtime_env)
    if h:
        parts.append(f"pip:{h}")
    ch = conda_env_hash(runtime_env)
    if ch:
        parts.append(f"conda:{ch}")
    c = (runtime_env or {}).get("container")
    if c:
        import hashlib
        spec = (c["image"], list(c.get("run_options") or []),
                c.get("runtime") or "",
                sorted((c.get("env_vars") or {}).items()))
        parts.append(
            "ctr:" + hashlib.sha1(repr(spec).encode()).hexdigest()[:16])
    return "+".join(parts) or None


# ---------------------------------------------------------------------------
# container isolation (reference: _private/runtime_env/container.py —
# worker commands wrapped in `podman run`)
# ---------------------------------------------------------------------------

def container_runtime(container: Dict[str, Any]) -> str:
    """Resolve the container runtime binary, honoring an explicit
    ``container['runtime']``.  Raises with a clear message when no runtime
    exists on the node (CI boxes without podman/docker)."""
    import shutil
    explicit = container.get("runtime")
    candidates = [explicit] if explicit else ["podman", "docker"]
    for c in candidates:
        path = shutil.which(c)
        if path:
            return path
    raise RuntimeError(
        f"runtime_env['container'] requires a container runtime "
        f"({' or '.join(candidates)}) on the node, but none was found "
        f"on PATH")


def container_worker_argv(container: Dict[str, Any], session_dir: str,
                          pkg_root: str, env: Dict[str, str],
                          passthrough: Optional[set] = None,
                          name: Optional[str] = None,
                          worker_module: str = "ray_tpu.core.worker_main"
                          ) -> list:
    """Build the argv that launches a worker inside the container.

    The container shares the host network (the worker dials the agent on
    127.0.0.1), the host IPC namespace + /dev/shm (the object store is a
    shm arena — without this, zero-copy reads cannot attach pool slices),
    the session dir (logs, spill, venv cache) and the framework source.
    Env passthrough is explicit (`run` starts from a clean environment by
    design): RAYTPU_*, the jax/TPU tuning vars, every key in
    ``passthrough`` (the agent passes its worker_env keys, so
    ``init(worker_env=...)`` behaves identically in and out of
    containers), plus container['env_vars'].  ``name`` makes the container
    addressable for teardown — killing the `run` CLIENT does not stop the
    container."""
    runtime = container_runtime(container)
    argv = [runtime, "run", "--rm", "--network=host", "--ipc=host",
            "-v", "/dev/shm:/dev/shm",
            "-v", f"{session_dir}:{session_dir}",
            "-v", f"{pkg_root}:{pkg_root}:ro"]
    if name:
        argv += ["--name", name]
    keep = set(passthrough or ())
    for k, v in env.items():
        if (k.startswith(("RAYTPU_", "JAX_", "XLA_", "TPU_", "LIBTPU_"))
                or k == "PYTHONPATH" or k in keep):
            argv += ["-e", f"{k}={v}"]
    for k, v in (container.get("env_vars") or {}).items():
        argv += ["-e", f"{k}={v}"]
    argv += list(container.get("run_options") or [])
    argv += [container["image"], "python", "-m", worker_module]
    return argv


_venv_locks: Dict[str, Any] = {}
_venv_guard = None


def materialize_pip_env(session_dir: str, runtime_env: Dict[str, Any]) -> str:
    """Build (or reuse) the venv for a pip runtime env; returns its python.

    Node-local URI cache: one venv per spec hash under
    ``{session_dir}/envs/{hash}`` with a ``.ready`` marker — concurrent
    requests for the same hash build once (per-hash lock).  The venv sees
    system site-packages (jax/numpy stay importable); pip installs overlay
    them (reference: pip.py creates the same system-site virtualenv).
    Runs in a worker thread — venv + pip take seconds."""
    import subprocess
    import sys
    import threading
    import venv as venv_mod

    global _venv_guard
    if _venv_guard is None:
        _venv_guard = threading.Lock()
    h = pip_env_hash(runtime_env)
    env_root = os.path.join(session_dir, "envs")
    env_dir = os.path.join(env_root, h)
    python = os.path.join(env_dir, "bin", "python")
    marker = os.path.join(env_dir, ".ready")
    with _venv_guard:
        lock = _venv_locks.setdefault(h, threading.Lock())
    os.makedirs(env_root, exist_ok=True)
    import fcntl
    lock_file = open(os.path.join(env_root, f".{h}.lock"), "w")
    try:
        with lock:
            # Cross-PROCESS exclusion too: every node agent of a local
            # cluster shares one session_dir, and venv.create(clear=True)
            # on a tree another agent is mid-install into destroys it.
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            if os.path.exists(marker):
                return python
            venv_mod.create(env_dir, system_site_packages=True,
                            with_pip=False, clear=True)
            # The building interpreter may itself be a venv, whose packages
            # system_site_packages does NOT expose (it points at the BASE
            # prefix).  A .pth appends this process's site-packages so jax/
            # numpy/cloudpickle stay importable; the env's own site-packages
            # comes first on sys.path, so pip installs below shadow them.
            import glob
            import site
            sp = glob.glob(os.path.join(env_dir, "lib", "python*",
                                        "site-packages"))[0]
            with open(os.path.join(sp, "_parent_sites.pth"), "w") as f:
                f.write("\n".join(site.getsitepackages()))
            # Install with the PARENT's pip targeting the env interpreter —
            # avoids a slow ensurepip bootstrap per env.  A string pip spec
            # is a requirements-file path (reference API form).
            cmd = [sys.executable, "-m", "pip", "--python", python,
                   "install", "--quiet", "--disable-pip-version-check"]
            cmd += list(runtime_env.get("pip_args") or [])
            pip = runtime_env["pip"]
            cmd += ["-r", pip] if isinstance(pip, str) else list(pip)
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=600)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"pip install failed for runtime env {h}: "
                    f"{proc.stderr[-2000:]}")
            with open(marker, "w") as f:
                f.write("ok")
            return python
    finally:
        lock_file.close()  # releases the flock


# ---------------------------------------------------------------------------
# conda isolation (reference: _private/runtime_env/conda.py — named envs
# activate, dict specs create content-hashed envs under the session dir)
# ---------------------------------------------------------------------------

def find_conda_exe() -> str:
    """Resolve the conda binary: RAYTPU_CONDA_EXE (the test seam and the
    operator override) beats PATH lookup of conda/mamba/micromamba."""
    import shutil
    explicit = os.environ.get("RAYTPU_CONDA_EXE")
    candidates = [explicit] if explicit else ["conda", "mamba", "micromamba"]
    for c in candidates:
        path = shutil.which(c)
        if path:
            return path
    raise RuntimeError(
        "runtime_env['conda'] requires a conda binary "
        f"({' or '.join(candidates)}) on the node, but none was found on "
        "PATH (set RAYTPU_CONDA_EXE to point at one)")


def materialize_conda_env(session_dir: str,
                          runtime_env: Dict[str, Any]) -> str:
    """Return the python interpreter of the env's conda environment.

    * name form (``conda="myenv"``): resolve the EXISTING env's python via
      ``conda run -n myenv python -c 'print(sys.executable)'`` — no
      mutation, matching the reference's activate-by-name path.
    * spec form (dict): ``conda env create -p {session}/conda/{hash}`` from
      the spec written as JSON (a YAML subset conda accepts), cached by
      content hash with a ``.ready`` marker + flock, exactly like the pip
      venv cache above.
    """
    import fcntl
    import subprocess

    conda_exe = find_conda_exe()
    conda = runtime_env["conda"]
    if isinstance(conda, str):
        proc = subprocess.run(
            [conda_exe, "run", "-n", conda, "python", "-c",
             "import sys; print(sys.executable)"],
            capture_output=True, text=True, timeout=120)
        if proc.returncode != 0 or not proc.stdout.strip():
            raise RuntimeError(
                f"conda env {conda!r} is not usable via {conda_exe}: "
                f"{proc.stderr[-2000:]}")
        return proc.stdout.strip().splitlines()[-1]

    h = conda_env_hash(runtime_env)
    env_root = os.path.join(session_dir, "conda")
    env_dir = os.path.join(env_root, h)
    python = os.path.join(env_dir, "bin", "python")
    marker = os.path.join(env_dir, ".ready")
    os.makedirs(env_root, exist_ok=True)
    lock_file = open(os.path.join(env_root, f".{h}.lock"), "w")
    try:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        if os.path.exists(marker):
            return python
        if os.path.isdir(env_dir):
            # a previous create died mid-install (no marker): conda
            # refuses to create into a non-empty prefix, so self-heal by
            # clearing it — the pip path's venv.create(clear=True)
            # equivalent
            import shutil
            shutil.rmtree(env_dir, ignore_errors=True)
        spec_path = os.path.join(env_root, f"{h}.yml")
        with open(spec_path, "w") as f:
            json.dump(conda, f)  # JSON is valid YAML: conda reads it
        proc = subprocess.run(
            [conda_exe, "env", "create", "-y", "-p", env_dir,
             "-f", spec_path],
            capture_output=True, text=True, timeout=1800)
        if proc.returncode != 0 or not os.path.exists(python):
            raise RuntimeError(
                f"conda env create failed for runtime env {h}: "
                f"{proc.stderr[-2000:]}")
        with open(marker, "w") as f:
            f.write("ok")
        return python
    finally:
        lock_file.close()  # releases the flock


def publish(gcs_call, job_id_hex: str, runtime_env: Dict[str, Any]):
    """Driver side: pack + store the env under the job id (reference:
    packaging.upload_package_if_needed)."""
    validate(runtime_env)
    blob: Dict[str, Any] = {"env_vars": dict(runtime_env.get("env_vars")
                                             or {})}
    mods = []
    for path in runtime_env.get("py_modules") or []:
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            raise ValueError(f"py_modules entry is not a directory: {path}")
        mods.append((os.path.basename(path), _pack_dir(path)))
    blob["py_modules"] = mods
    if runtime_env.get("working_dir"):
        blob["working_dir"] = _pack_dir(runtime_env["working_dir"])
    gcs_call("kv_put", ns=NS, key=job_id_hex, value=cloudpickle.dumps(blob))


_materialized: set = set()
#: per-job process-level mutations (env_vars, cwd) for re-application when a
#: shared worker interleaves tasks of different jobs
_applied_state: dict = {}
_last_applied: Optional[str] = None


def ensure(worker, job_id_hex: str):
    """Worker side: materialize the job's env once (idempotent, cheap on the
    hot path — one KV miss per job when no env exists).  The job is marked
    materialized only AFTER success, so a transient GCS/extract failure
    retries on the next task instead of silently disabling the env.

    Workers are shared across jobs, so the process-wide pieces (env vars,
    cwd) RE-apply whenever the executing job changes — sys.path additions
    accumulate (harmless: packages are namespaced per job dir)."""
    global _last_applied
    if job_id_hex in _materialized:
        if _last_applied != job_id_hex:
            _reapply(job_id_hex)
        return
    from .rpc import run_async

    raw = run_async(worker.gcs.call_retry("kv_get", ns=NS, key=job_id_hex,
                                          _idempotent=False))
    if raw is None:
        _materialized.add(job_id_hex)
        _applied_state[job_id_hex] = None
        if _last_applied != job_id_hex:
            _last_applied = job_id_hex
        return
    blob = cloudpickle.loads(raw)
    base = os.path.join(worker.session_dir, "runtime_envs", job_id_hex)
    for name, data in blob.get("py_modules", []):
        dest = os.path.join(base, "py_modules", name)
        if not os.path.isdir(dest):
            os.makedirs(dest, exist_ok=True)
            with tarfile.open(fileobj=io.BytesIO(data)) as tf:
                tf.extractall(dest, filter="data")
        parent = os.path.dirname(dest)
        if parent not in sys.path:
            sys.path.insert(0, parent)
    if blob.get("working_dir"):
        dest = os.path.join(base, "working_dir")
        if not os.path.isdir(dest):
            os.makedirs(dest, exist_ok=True)
            with tarfile.open(fileobj=io.BytesIO(
                    blob["working_dir"])) as tf:
                tf.extractall(dest, filter="data")
        if dest not in sys.path:
            sys.path.insert(0, dest)
        os.chdir(dest)
    for k, v in blob.get("env_vars", {}).items():
        os.environ[k] = str(v)
    _applied_state[job_id_hex] = {
        "env_vars": dict(blob.get("env_vars", {})),
        "cwd": (os.path.join(base, "working_dir")
                if blob.get("working_dir") else None),
    }
    _materialized.add(job_id_hex)
    _last_applied = job_id_hex


def _reapply(job_id_hex: str):
    global _last_applied
    state = _applied_state.get(job_id_hex)
    _last_applied = job_id_hex
    if not state:
        return
    for k, v in state["env_vars"].items():
        os.environ[k] = str(v)
    if state["cwd"] and os.path.isdir(state["cwd"]):
        os.chdir(state["cwd"])
