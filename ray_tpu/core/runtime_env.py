"""Runtime environments: shipping code + env vars to every worker.

Reference: ``python/ray/_private/runtime_env/`` — the agent materializes
per-job environments (working_dir/py_modules packaged through the GCS,
``packaging.py``; agent ``runtime_env_agent.py:159``).  Scope here: the
job-level environment — ``py_modules`` directories and ``env_vars`` packed
at ``ray_tpu.init(runtime_env=...)`` into the GCS KV; every worker
materializes them once per job before executing that job's first task, so
multi-node deployments distribute real packages, not just cloudpickle
closures.  (conda/pip env building is out of scope on a no-network image;
the plug point is ``_materialize``.)
"""

from __future__ import annotations

import io
import os
import sys
import tarfile
from typing import Any, Dict, List, Optional

import cloudpickle

NS = "runtime_envs"


def _pack_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for root, _dirs, files in os.walk(path):
            for fn in files:
                if fn.endswith((".pyc", ".so.tmp")) or "__pycache__" in root:
                    continue
                full = os.path.join(root, fn)
                tf.add(full, arcname=os.path.relpath(full, path))
    return buf.getvalue()


def validate(runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    known = {"py_modules", "env_vars", "working_dir"}
    unknown = set(runtime_env) - known
    if unknown:
        raise ValueError(f"unsupported runtime_env keys: {sorted(unknown)} "
                         f"(supported: {sorted(known)})")
    return runtime_env


def publish(gcs_call, job_id_hex: str, runtime_env: Dict[str, Any]):
    """Driver side: pack + store the env under the job id (reference:
    packaging.upload_package_if_needed)."""
    validate(runtime_env)
    blob: Dict[str, Any] = {"env_vars": dict(runtime_env.get("env_vars")
                                             or {})}
    mods = []
    for path in runtime_env.get("py_modules") or []:
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            raise ValueError(f"py_modules entry is not a directory: {path}")
        mods.append((os.path.basename(path), _pack_dir(path)))
    blob["py_modules"] = mods
    if runtime_env.get("working_dir"):
        blob["working_dir"] = _pack_dir(runtime_env["working_dir"])
    gcs_call("kv_put", ns=NS, key=job_id_hex, value=cloudpickle.dumps(blob))


_materialized: set = set()
#: per-job process-level mutations (env_vars, cwd) for re-application when a
#: shared worker interleaves tasks of different jobs
_applied_state: dict = {}
_last_applied: Optional[str] = None


def ensure(worker, job_id_hex: str):
    """Worker side: materialize the job's env once (idempotent, cheap on the
    hot path — one KV miss per job when no env exists).  The job is marked
    materialized only AFTER success, so a transient GCS/extract failure
    retries on the next task instead of silently disabling the env.

    Workers are shared across jobs, so the process-wide pieces (env vars,
    cwd) RE-apply whenever the executing job changes — sys.path additions
    accumulate (harmless: packages are namespaced per job dir)."""
    global _last_applied
    if job_id_hex in _materialized:
        if _last_applied != job_id_hex:
            _reapply(job_id_hex)
        return
    from .rpc import run_async

    raw = run_async(worker.gcs.call("kv_get", ns=NS, key=job_id_hex))
    if raw is None:
        _materialized.add(job_id_hex)
        _applied_state[job_id_hex] = None
        if _last_applied != job_id_hex:
            _last_applied = job_id_hex
        return
    blob = cloudpickle.loads(raw)
    base = os.path.join(worker.session_dir, "runtime_envs", job_id_hex)
    for name, data in blob.get("py_modules", []):
        dest = os.path.join(base, "py_modules", name)
        if not os.path.isdir(dest):
            os.makedirs(dest, exist_ok=True)
            with tarfile.open(fileobj=io.BytesIO(data)) as tf:
                tf.extractall(dest, filter="data")
        parent = os.path.dirname(dest)
        if parent not in sys.path:
            sys.path.insert(0, parent)
    if blob.get("working_dir"):
        dest = os.path.join(base, "working_dir")
        if not os.path.isdir(dest):
            os.makedirs(dest, exist_ok=True)
            with tarfile.open(fileobj=io.BytesIO(
                    blob["working_dir"])) as tf:
                tf.extractall(dest, filter="data")
        if dest not in sys.path:
            sys.path.insert(0, dest)
        os.chdir(dest)
    for k, v in blob.get("env_vars", {}).items():
        os.environ[k] = str(v)
    _applied_state[job_id_hex] = {
        "env_vars": dict(blob.get("env_vars", {})),
        "cwd": (os.path.join(base, "working_dir")
                if blob.get("working_dir") else None),
    }
    _materialized.add(job_id_hex)
    _last_applied = job_id_hex


def _reapply(job_id_hex: str):
    global _last_applied
    state = _applied_state.get(job_id_hex)
    _last_applied = job_id_hex
    if not state:
        return
    for k, v in state["env_vars"].items():
        os.environ[k] = str(v)
    if state["cwd"] and os.path.isdir(state["cwd"]):
        os.chdir(state["cwd"])
