"""External (fsspec-backed) object spill tier.

The durability leg of the object plane (reference: the raylet's
``object_spilling_config`` with smart_open/fsspec URIs): spilled objects
are written ONCE to a cluster-readable URI (``gs://bucket/prefix`` in
production, ``file:///dir`` in tests) and registered with the owner as a
*location that is not a node* — the sentinel node id
:data:`EXTERNAL_NODE_ID` paired with the object's URI rides the normal
``add_object_location`` path, flows through the owner's location list, and
is accepted by **any** node's pull path as a valid chunk source
(``NodeAgent._fetch_chunk`` range-reads the URI instead of issuing a
``read_chunk`` RPC).  Losing the node that spilled the object therefore no
longer loses the object.

Layout is deterministic — ``{base_uri}/{object_id.hex()}.obj`` — so every
process derives the same URI from the same id; no directory listing on the
read path.  All IO goes through fsspec; for ``file://`` URIs a plain-os
fallback keeps the tier working even where fsspec is absent.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

#: sentinel ``node_id`` for owner location entries that point at an
#: external URI rather than a node agent (address field = the URI)
EXTERNAL_NODE_ID = "external"

_OBJ_SUFFIX = ".obj"


def is_external_address(addr: str) -> bool:
    """True for location ADDRESSES that are external-tier URIs, not
    ``host:port`` agent endpoints (every fsspec URI carries a scheme)."""
    return "://" in (addr or "")


def object_uri(base_uri: str, object_id) -> str:
    """Deterministic per-object URI under the external tier base."""
    hexid = object_id.hex() if hasattr(object_id, "hex") else str(object_id)
    return f"{base_uri.rstrip('/')}/{hexid}{_OBJ_SUFFIX}"


# ------------------------------------------------------------- self-metrics

def _build_spill_metrics():
    from ray_tpu.util.metrics import Counter, Histogram
    return {
        "bytes": Counter(
            "raytpu_spill_bytes_total",
            "object bytes spilled out of the shm store, by tier",
            tag_keys=("tier",)),
        "restore_seconds": Histogram(
            "raytpu_spill_restore_seconds",
            "spilled-object restore latency (read -> resident in store)",
            boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                        2.5, 5.0, 15.0, 60.0]),
    }


_spill_metrics_get = None

KEY_TIER_LOCAL = (("tier", "local"),)
KEY_TIER_EXTERNAL = (("tier", "external"),)


def spill_metrics():
    global _spill_metrics_get
    if _spill_metrics_get is None:
        # deferred to first call: importing util.metrics at module import
        # time re-enters the ray_tpu package init (circular import)
        from ray_tpu.util.metrics import lazy
        _spill_metrics_get = lazy(_build_spill_metrics)
    return _spill_metrics_get()


# ------------------------------------------------------------------ file IO
#
# fsspec when available (gs://, s3://, any registered scheme); a plain-os
# fallback for file:// so the tier works in minimal environments.  Tests
# monkeypatch these four functions to inject slowness/failures.

def _file_path(uri: str) -> Optional[str]:
    if uri.startswith("file://"):
        return uri[len("file://"):]
    return None


def _fs_and_path(uri: str):
    import fsspec
    return fsspec.core.url_to_fs(uri)


def write(uri: str, data) -> int:
    """Write ``data`` to ``uri`` (parents created); returns bytes written."""
    p = _file_path(uri)
    if p is not None:
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = f"{p}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)  # readers never observe a partial object
        return len(data)
    fs, path = _fs_and_path(uri)
    fs.makedirs(os.path.dirname(path), exist_ok=True)
    with fs.open(path, "wb") as f:
        f.write(bytes(data))
    return len(data)


def read(uri: str) -> bytes:
    p = _file_path(uri)
    if p is not None:
        with open(p, "rb") as f:
            return f.read()
    fs, path = _fs_and_path(uri)
    with fs.open(path, "rb") as f:
        return f.read()


def read_range(uri: str, offset: int, length: int) -> bytes:
    """Range read — the chunk-source primitive the transfer plane stripes
    over (an external URI participates in a ``StripedPull`` exactly like a
    node source, one chunk at a time)."""
    p = _file_path(uri)
    if p is not None:
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(length)
    fs, path = _fs_and_path(uri)
    with fs.open(path, "rb") as f:
        f.seek(offset)
        return f.read(length)


def exists(uri: str) -> bool:
    p = _file_path(uri)
    if p is not None:
        return os.path.exists(p)
    try:
        fs, path = _fs_and_path(uri)
        return fs.exists(path)
    except Exception:
        return False


def delete(uri: str) -> bool:
    p = _file_path(uri)
    if p is not None:
        try:
            os.unlink(p)
            return True
        except OSError:
            return False
    try:
        fs, path = _fs_and_path(uri)
        fs.rm(path)
        return True
    except Exception:
        return False


def size(uri: str) -> Optional[int]:
    p = _file_path(uri)
    if p is not None:
        try:
            return os.path.getsize(p)
        except OSError:
            return None
    try:
        fs, path = _fs_and_path(uri)
        return fs.size(path)
    except Exception:
        return None


def list_objects(base_uri: str) -> List[str]:
    """Object URIs currently under the tier base (ops/debug surface)."""
    p = _file_path(base_uri)
    out: List[str] = []
    if p is not None:
        try:
            names = os.listdir(p)
        except OSError:
            return []
        return [f"{base_uri.rstrip('/')}/{n}" for n in sorted(names)
                if n.endswith(_OBJ_SUFFIX)]
    try:
        fs, path = _fs_and_path(base_uri)
        for entry in fs.ls(path, detail=False):
            if str(entry).endswith(_OBJ_SUFFIX):
                out.append(f"{base_uri.split('://', 1)[0]}://{entry}")
    except Exception:
        return []
    return sorted(out)


def timed_read(uri: str) -> bytes:
    """Read + observe ``raytpu_spill_restore_seconds``."""
    t0 = time.monotonic()
    data = read(uri)
    m = spill_metrics()
    if m is not None:
        m["restore_seconds"].observe(time.monotonic() - t0)
    return data
