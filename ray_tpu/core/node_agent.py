"""Node agent — the per-node runtime daemon (raylet-equivalent).

Plays the role of the reference's raylet (``src/ray/raylet/node_manager.h:125``):

* **Worker pool** — spawns/pools worker subprocesses, prestart, idle reaping
  (reference: ``worker_pool.h:152``).
* **Worker leases** — clients request a lease for a task's resource demand; the agent
  grants an idle/new worker, queues when saturated, or replies with a *spillback* target
  chosen from the cluster view (reference: ``ClusterTaskManager`` queue + spillback,
  ``cluster_task_manager.h:42``; ``HandleRequestWorkerLease`` ``node_manager.cc:1776``).
* **Actor creation** — GCS delegates placement here: the agent leases a dedicated worker
  and pushes the actor-creation task to it (reference: ``GcsActorScheduler`` leasing via
  the same RequestWorkerLease path).
* **Placement-group bundles** — 2-phase prepare/commit resource reservation
  (reference: ``placement_group_resource_manager.h``, ``node_manager.proto:388-395``).
* **Object store service** — hosts the node's shared-memory store; serves create/seal/
  get/free plus chunked node-to-node pulls with admission control (reference: plasma in
  raylet + ``ObjectManager``/``PullManager``, ``object_manager.h:117``, ``pull_manager.h:52``).
* **Health** — heartbeats to GCS with available resources + queue length; monitors worker
  subprocesses and reports actor deaths (reference: heartbeats +
  ``NodeManager::HandleUnexpectedWorkerFailure``).
"""

from __future__ import annotations

import asyncio
import collections
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import chaos, external_spill, object_explain, sched_explain
from .common import ResourceSet, TaskSpec, detect_node_resources
from .config import get_config
from .external_spill import EXTERNAL_NODE_ID, is_external_address
from .ids import NodeID, ObjectID, WorkerID
from .object_store import (ChunkNotAvailable, NodeObjectStore,
                           ObjectStoreFullError, sweep_orphan_spill_dirs)
from .rpc import (ClientPool, ConnectionLost, RemoteError, RpcClient,
                  RpcServer, TransientServerError)
from .scheduling import NodeView, pick_node
from .transfer import (KEY_CHUNK_OUT, KEY_PROXY_IN, ChunkCrcError,
                       ChunkLedger, StripedPull, chunk_checksum,
                       transfer_metrics)

#: True when the asyncio selector transport COPIES unsent write() bytes
#: into its own buffer before returning (<= 3.11).  3.12+ retains the
#: caller's buffer in a zero-copy write queue across loop ticks, so a
#: shm view handed to write() could dangle past an arena recycle.
_TRANSPORT_COPIES_WRITES = sys.version_info < (3, 12)


def _owned_reply_buffer(view: memoryview) -> memoryview:
    """The RPC chunk reply's out-of-band buffer: the zero-copy store view
    itself where the transport consumes writes synchronously (the
    same-tick no-recycle argument in handle_read_chunk), else a
    DELIBERATE defensive copy — on 3.12+ transports the unsent remainder
    of a reply stays a live view across loop ticks, and serving a
    recycled arena range would ship another object's bytes.  The bulk
    channel (pin-protected sends) is the zero-copy path either way."""
    if _TRANSPORT_COPIES_WRITES:
        return view
    return memoryview(bytes(view))

# Lazy singleton: node telemetry gauges (reference: metric_defs.cc core
# metrics).  Module-level so in-process multi-agent clusters (tests, the
# driver-embedded head) share one registry entry per name — each agent's
# samples are separated by the `node` tag.
def _build_telemetry_gauges():
    from ray_tpu.util.metrics import Gauge
    return {
        "workers": Gauge(
            "raytpu_node_workers",
            "worker processes registered to this agent", tag_keys=("node",)),
        "workers_leased": Gauge(
            "raytpu_node_workers_leased",
            "workers currently executing under a lease", tag_keys=("node",)),
        "lease_queue": Gauge(
            "raytpu_node_lease_queue_len",
            "lease requests queued (scheduler backlog)", tag_keys=("node",)),
        "store_used": Gauge(
            "raytpu_object_store_bytes",
            "shm pool bytes in use", tag_keys=("node",)),
        "store_capacity": Gauge(
            "raytpu_object_store_capacity_bytes",
            "shm pool capacity", tag_keys=("node",)),
        "store_free": Gauge(
            "raytpu_object_store_free_bytes",
            "shm pool bytes free", tag_keys=("node",)),
        "store_largest_free": Gauge(
            "raytpu_object_store_largest_free_bytes",
            "largest contiguous free shm block", tag_keys=("node",)),
        "store_objects": Gauge(
            "raytpu_object_store_objects",
            "sealed objects resident in the store", tag_keys=("node",)),
        "store_pinned": Gauge(
            "raytpu_object_store_pinned",
            "store entries with a live pin", tag_keys=("node",)),
        "read_pins": Gauge(
            "raytpu_read_pins_outstanding",
            "zero-copy read pins granted and not yet released",
            tag_keys=("node",)),
        "oom_kills": Gauge(
            "raytpu_node_oom_kills",
            "memory-monitor worker kills since agent start",
            tag_keys=("node",)),
        "resource_available": Gauge(
            "raytpu_resource_available",
            "schedulable capacity currently free",
            tag_keys=("node", "resource")),
        "resource_total": Gauge(
            "raytpu_resource_total",
            "schedulable capacity", tag_keys=("node", "resource")),
        # -- object-plane memory gauges (object_metrics_enabled) --------
        "mem_frag": Gauge(
            "raytpu_mem_arena_frag_fraction",
            "shm arena fragmentation (1 - largest_free/free; 0 = one "
            "contiguous free region)", tag_keys=("node",)),
        "mem_free_blocks": Gauge(
            "raytpu_mem_arena_free_blocks",
            "free blocks in the shm arena (sliver accumulation signal)",
            tag_keys=("node",)),
        "mem_spill_bytes": Gauge(
            "raytpu_mem_spill_bytes",
            "bytes currently resident on a spill tier, by tier",
            tag_keys=("node", "tier")),
        "mem_spill_objects": Gauge(
            "raytpu_mem_spill_objects",
            "objects currently resident on a spill tier, by tier",
            tag_keys=("node", "tier")),
        "mem_leaks": Gauge(
            "raytpu_mem_leak_suspects",
            "ref-debt suspects on this node (pins past TTL + deferred "
            "frees stuck behind vanished pins)", tag_keys=("node",)),
        "disk_used_frac": Gauge(
            "raytpu_node_disk_used_fraction",
            "used fraction of the filesystem holding the session dir "
            "(logs + local spill) — the health plane's DISK_LOW input",
            tag_keys=("node",)),
        "disk_free": Gauge(
            "raytpu_node_disk_free_bytes",
            "free bytes on the session-dir filesystem",
            tag_keys=("node",)),
    }


_telemetry_gauges_get = None


def _telemetry_gauges():
    global _telemetry_gauges_get
    if _telemetry_gauges_get is None:
        # deferred to first call: importing util.metrics at module import
        # time re-enters the ray_tpu package init (circular import)
        from ray_tpu.util.metrics import lazy
        _telemetry_gauges_get = lazy(_build_telemetry_gauges)
    return _telemetry_gauges_get()


@dataclass
class WorkerHandle:
    worker_id: str
    proc: Optional[asyncio.subprocess.Process]
    state: str = "STARTING"          # STARTING | IDLE | LEASED | DRAINING | DEAD
    address: str = ""
    pid: int = 0
    lease_id: Optional[str] = None
    is_actor: bool = False
    actor_id: Optional[str] = None
    probe_failures: int = 0          # consecutive failed idle-reaper probes
    blocked: bool = False
    idle_since: float = field(default_factory=time.monotonic)
    leased_at: float = 0.0           # last IDLE->LEASED transition
    registered: "asyncio.Event" = field(default_factory=asyncio.Event)
    #: pip-env identity: workers run the env's venv interpreter and are only
    #: leased to tasks with the same hash (None = the plain interpreter)
    env_hash: Optional[str] = None
    #: lease provenance for the group-by-owner OOM policy: the submitting
    #: CoreWorker's address and its scheduling-key label
    owner: Optional[str] = None
    task_label: str = ""
    #: (runtime_path, container_name) for containerized workers — killing
    #: the `run` client does not stop the container; teardown must `rm -f`.
    container_ref: Optional[tuple] = None
    #: exit_actor(): the coming process exit is INTENDED — the exit
    #: backstop must report expected=True, never burn a restart
    intended_exit: bool = False


@dataclass
class LeaseRequest:
    lease_id: str
    resources: Dict[str, float]
    bundle: Optional[Tuple[str, int]]  # (pg_id, bundle_index)
    future: "asyncio.Future"
    runtime_env: Optional[dict] = None
    allow_spillback: bool = True
    owner: Optional[str] = None
    task_label: str = ""
    #: the connection the request arrived on: a queued request whose
    #: requester disconnected must NOT be granted a worker nobody will
    #: ever use (the grant would leak the node's capacity forever)
    writer: Optional[object] = None


class NodeAgent:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1", port: int = 0,
                 num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 session_dir: str = "/tmp/raytpu",
                 worker_env: Optional[Dict[str, str]] = None,
                 object_store_memory: int = 0):
        self.node_id = NodeID.from_random()
        self.gcs_address = gcs_address
        self.server = RpcServer(self, host, port, bulk_replies=True)
        self.total = ResourceSet(detect_node_resources(num_cpus, num_tpus, resources))
        self.available = ResourceSet(self.total.to_dict())
        self.labels = dict(labels or {})
        self.labels.setdefault("node_id", self.node_id.hex())
        self.store = NodeObjectStore(self.node_id.hex()[:12], object_store_memory)
        self.workers: Dict[str, WorkerHandle] = {}
        # O(1) dispatch fast path: per-env-hash MRU stack of idle worker
        # ids.  Entries are validated on pop (lazy deletion), so a state
        # change that bypassed the queue can never hand out a stale worker;
        # the full O(n) scan remains as the empty-queue fallback.
        self._idle_ready: Dict[Optional[str], "collections.deque[str]"] = {}
        self.lease_queue: List[LeaseRequest] = []
        self.bundles: Dict[Tuple[str, int], ResourceSet] = {}       # committed
        self.prepared_bundles: Dict[Tuple[str, int], ResourceSet] = {}
        self.gcs: Optional[RpcClient] = None
        self.worker_clients = ClientPool()
        self.agent_clients = ClientPool()
        self.cluster_view: Dict[str, NodeView] = {}
        #: last replayed seq of the GCS dead-lease-owner broadcast (heartbeat
        #: piggyback, same convergence pattern as chaos/shard_map)
        self._dead_owners_seq = 0
        self.session_dir = session_dir
        self.worker_env = dict(worker_env or {})
        self._bg: List[asyncio.Task] = []
        self._pull_sem = asyncio.Semaphore(get_config().object_pull_max_concurrency)
        self._inflight_pulls: Dict[ObjectID, "asyncio.Future"] = {}
        self._lease_counter = 0
        self._shutting_down = False
        # Preemption drain state: while draining the agent answers every
        # lease request with backpressure (owners re-pick a node), spills
        # sole-copy objects to the external tier / a peer, waits for
        # outstanding leases to return, then deregisters — with a hard
        # cutoff at the preemption notice deadline.
        self._draining = False
        self._preempt_task: Optional[asyncio.Task] = None
        #: standalone-process hook (node_main sets os._exit): a preempted
        #: node's process must actually disappear; in-process agents
        #: (tests, the driver-embedded head) fall back to stop()
        self._on_preempt_exit = None
        # Same-host identity for zero-copy object sharing: two agents with
        # equal host_key share one /dev/shm, so a "transfer" between them is
        # an mmap attach of the source's pool slice (plasma same-node
        # sharing, generalized across agents).
        import socket as _socket
        try:
            shm_dev = os.stat("/dev/shm").st_dev if os.path.isdir(
                "/dev/shm") else 0
        except OSError:
            shm_dev = 0
        self.host_key = f"{_socket.gethostname()}:{shm_dev}"
        # Read-pin bookkeeping by CONSUMER address (the plasma analogue of
        # releasing a client's pins on socket disconnect): a worker that
        # dies with live zero-copy views — OOM kill, crash — never sends
        # its store_unpin_read, so _on_worker_exit drains its pins here
        # instead of leaking the objects unevictable forever.  Each grant
        # records the store-record KIND it pinned ("local"/"proxy", from
        # pin_for_read) so the release decrements the same record:
        # {consumer_addr: {object_id: {kind: count}}}.
        self._read_pins: Dict[str, Dict[ObjectID, Dict[str, int]]] = {}
        # chaos plane: last runtime spec version applied from the GCS, the
        # kill-schedule task driven by the installed injector, and the
        # runtime spec itself — forwarded to workers spawned AFTER a
        # chaos_set (their RAYTPU_CONFIG_JSON predates it)
        self._chaos_version = 0
        self._chaos_kill_task: Optional[asyncio.Task] = None
        self._chaos_runtime_spec: Optional[dict] = None
        self._chaos_runtime_applied = False
        # Backpressure-reject accounting (the lease-queue admission
        # control's visible half): plain counters always (node_info,
        # bench_scale read them), mirrored into
        # raytpu_sched_backpressure_total{node,reason} when
        # sched_metrics_enabled.  reason in {"depth", "draining"}.
        self._bp_rejects: Dict[str, int] = {}
        self._bp_keys: Dict[str, tuple] = {}
        # worker_id -> memory-monitor kill cause, consumed by the lease
        # return so the owner raises a typed OutOfMemoryError.
        self._oom_kills: Dict[str, str] = {}
        self._oom_kill_count = 0  # lifetime total, exported in stats
        # strong refs to fire-and-forget loop tasks (event writes): the
        # event loop itself only holds weak references
        self._bg_tasks: set = set()
        # per-(owner, object) tail of the location-update chain (see
        # _location_update: add/remove must apply in issue order)
        self._loc_updates: Dict[Tuple[str, ObjectID], "asyncio.Task"] = {}
        # Object-plane flight recorder (core/object_explain.py): bounded
        # buffer of lifecycle transition events flushed to the GCS ring,
        # a bounded ring of completed-pull ChunkLedger end-states
        # (state.transfers()), and first-grant timestamps per (pinner,
        # object) for the pin-TTL leak detector.  All empty/unwritten
        # when object_metrics_enabled is off.
        self._object_events: List[dict] = []
        self._object_events_dropped = 0
        self._transfer_ring: collections.deque = collections.deque(
            maxlen=max(16, get_config().object_transfer_ring_len))
        self._pin_first_ts: Dict[Tuple[str, ObjectID], float] = {}
        self.store.on_object_event = self._buffer_object_event
        # Bulk transfer channel (core/bulk_transfer.py): threaded
        # blocking-socket chunk serving/landing beside the asyncio RPC
        # plane.  Server started in start(); client sockets + the landing
        # executor are lazy.  _bulk_addrs caches peer bulk addresses
        # (None = resolution in flight, False = peer has none).
        self._bulk_server = None
        self._bulk_pool = None
        self._bulk_addrs: Dict[str, object] = {}
        self._transfer_pool = None

    # ------------------------------------------------------------------ boot

    async def start(self):
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        # Orphan sweep: a previous incarnation of a node on this host that
        # died (preemption, SIGKILL) left spill files nothing will ever
        # restore — delete dirs whose writing pid is gone before this
        # incarnation starts accumulating its own.
        if self.store.spill_root:
            try:
                sweep_orphan_spill_dirs(self.store.spill_root)
            except Exception:
                pass
        # External-spill registration hook: once a spill write LANDS, tell
        # the object's owner the external URI is a location (marshalled
        # from the writer thread back onto this agent's loop).
        loop = asyncio.get_event_loop()

        def _on_ext_spill(oid, uri, owner, _loop=loop):
            if owner:
                _loop.call_soon_threadsafe(
                    self._location_update, owner, "add_object_location",
                    oid, EXTERNAL_NODE_ID, uri)

        self.store.on_external_spill = _on_ext_spill
        await self.server.start()
        try:
            from .bulk_transfer import BulkServer

            def _on_bulk_sent(nbytes: int):
                m = transfer_metrics()
                if m is not None:  # Counter.inc_key is lock-protected
                    m["bytes"].inc_key(KEY_CHUNK_OUT, nbytes)

            self._bulk_server = BulkServer(self._bulk_acquire,
                                           self._bulk_release, loop,
                                           host=self.server.host,
                                           on_sent=_on_bulk_sent)
        except Exception:
            self._bulk_server = None  # peers fall back to the RPC path
        if get_config().metrics_export_enabled:
            # before registration: the endpoint port rides the node labels
            await self._start_metrics_endpoint()
        # Shard-aware control-plane client (core/gcs_router.py): this
        # agent's hot fan-in traffic (object-event flushes) goes direct to
        # its shard; register/heartbeat/lease concerns stay on the router.
        from .gcs_router import ShardedGcsClient
        self.gcs = ShardedGcsClient(self.gcs_address,
                                    identity=self.node_id.hex())
        # retried registration with an idempotency token: a lost reply (GCS
        # blip, chaos drop) must not register this node twice
        res = await self.gcs.call_retry(
            "register_node", node_id=self.node_id.hex(),
            address=self.server.address,
            resources=self.total.to_dict(), labels=self.labels)
        self._apply_view(res["cluster_view"])
        self.gcs.apply_shard_map(res.get("shard_map"))
        # start at the GCS's current dead-owner seq: everything before it
        # predates this node (no leases to reclaim), and a fresh agent
        # heartbeating seq=0 would otherwise replay the whole deque
        self._dead_owners_seq = int(res.get("dead_owners_seq", 0))
        # config/env chaos spec: arm the kill schedule (if any) at boot
        self._arm_chaos_schedule()
        self._bg.append(asyncio.ensure_future(self._heartbeat_loop()))
        if get_config().metrics_export_enabled:
            self._bg.append(asyncio.ensure_future(self._telemetry_loop()))
        self._bg.append(asyncio.ensure_future(self._idle_reaper_loop()))
        self._bg.append(asyncio.ensure_future(self._pin_sweep_loop()))
        self._bg.append(asyncio.ensure_future(self._flush_object_events_loop()))
        self._bg.append(asyncio.ensure_future(self._log_monitor_loop()))
        self._bg.append(asyncio.ensure_future(self._memory_monitor_loop()))
        cfg = get_config()
        for _ in range(cfg.prestart_workers):
            asyncio.ensure_future(self._spawn_worker())
        from ray_tpu.util.loop_monitor import install as _install_loop_mon
        self._loop_monitor = _install_loop_mon(
            asyncio.get_event_loop(), f"node_agent:{self.node_id.hex()[:12]}",
            gcs_call=self.gcs.call)
        return self

    @property
    def address(self) -> str:
        return self.server.address

    async def stop(self):
        self._shutting_down = True
        if getattr(self, "_loop_monitor", None):
            self._loop_monitor.stop()
        if self._chaos_kill_task is not None:
            self._chaos_kill_task.cancel()
        for t in self._bg:
            t.cancel()
        for w in list(self.workers.values()):
            await self._kill_worker_proc(w)
        await self.worker_clients.close_all()
        await self.agent_clients.close_all()
        if self.gcs:
            await self.gcs.close()
        if self._bulk_server is not None:
            self._bulk_server.close()
        if self._bulk_pool is not None:
            self._bulk_pool.close()
        if self._transfer_pool is not None:
            self._transfer_pool.shutdown(wait=False)
        await self.server.stop()
        self.store.shutdown()

    def _aggregate_demands(self, max_shapes: int = 50):
        """Queued lease demands as (shape, count) pairs — a wide fan-out must
        not serialize thousands of identical dicts into every heartbeat
        (reference: load reporting aggregates by shape)."""
        counts: Dict[tuple, int] = {}
        for r in self.lease_queue:
            key = tuple(sorted(r.resources.items()))
            counts[key] = counts.get(key, 0) + 1
        return [[dict(k), c] for k, c in list(counts.items())[:max_shapes]]

    def _aggregate_task_leases(self) -> Dict[str, float]:
        """Resources held by short-lived task leases (non-actor, outside any
        PG bundle; blocked leases already released theirs).  Rides the
        heartbeat so elastic capacity probes can treat this slice of a
        busy node as reclaimable headroom rather than permanent load."""
        out: Dict[str, float] = {}
        for w in self.workers.values():
            if (w.state == "LEASED" and w.lease_id and not w.is_actor
                    and not w.blocked
                    and w.lease_id not in self._bundle_of_lease):
                for k, v in (self._lease_resources.get(w.lease_id)
                             or {}).items():
                    out[k] = out.get(k, 0.0) + v
        return out

    def _apply_view(self, payload: Dict[str, dict]):
        self.cluster_view = {
            nid: NodeView(nid, d["address"], d["total"], d["available"],
                          d.get("labels", {}), d.get("alive", True),
                          d.get("queue_len", 0), d.get("draining", False),
                          d.get("task_leased", {}))
            for nid, d in payload.items()}

    async def _heartbeat_loop(self):
        cfg = get_config()
        while not self._shutting_down:
            try:
                res = await self.gcs.call(
                    "heartbeat", node_id=self.node_id.hex(),
                    available=self.available.to_dict(),
                    # total rides every heartbeat so a lost
                    # update_node_resources push self-heals (dynamic
                    # set_resource changes capacity at runtime)
                    total=self.total.to_dict(),
                    queue_len=len(self.lease_queue),
                    queued_demands=self._aggregate_demands(),
                    store_stats=self.store.stats(),
                    chaos_version=self._chaos_version,
                    draining=self._draining,
                    shard_map_version=self.gcs.shard_map_version,
                    dead_owners_seq=self._dead_owners_seq,
                    task_leased=self._aggregate_task_leases())
                if res.get("unknown"):
                    res2 = await self.gcs.call_retry(
                        "register_node", node_id=self.node_id.hex(),
                        address=self.server.address,
                        resources=self.total.to_dict(), labels=self.labels)
                    self._apply_view(res2["cluster_view"])
                    self.gcs.apply_shard_map(res2.get("shard_map"))
                    # adopt the (possibly restarted) GCS's dead-owner seq:
                    # keeping our old, higher counter would make the
                    # heartbeat's `seq < gcs_seq` check silently skip
                    # every new dead-owner broadcast until it caught up
                    self._dead_owners_seq = int(
                        res2.get("dead_owners_seq", 0))
                elif "view" in res:
                    self._apply_view(res["view"])
                if "shard_map" in res:
                    # a shard respawned (or sharding just turned on):
                    # converge via the same piggyback pattern as chaos —
                    # independent of the view above (a reply can carry both)
                    self.gcs.apply_shard_map(res["shard_map"])
                if "chaos" in res:
                    # runtime chaos spec changed at the GCS (chaos_set /
                    # chaos_clear): converge via the heartbeat piggyback
                    await self._apply_chaos(res["chaos"]["spec"],
                                            res["chaos"]["version"])
                if "dead_owners" in res:
                    # confirmed-dead lease owners (killed/crashed actors):
                    # reclaim their orphaned task-worker leases NOW instead
                    # of waiting out the pin sweep's 3-strike probe — an
                    # elastic re-form may be queued on the freed slot
                    self._dead_owners_seq = res["dead_owners"]["seq"]
                    for addr in res["dead_owners"]["addrs"]:
                        await self._drain_read_pins(addr)
                        await self._reclaim_dead_owner_leases(addr)
                if self.lease_queue:
                    await self._process_lease_queue()
            except Exception:
                await asyncio.sleep(0.5)
            await asyncio.sleep(cfg.resource_broadcast_period_s)

    async def _idle_reaper_loop(self):
        cfg = get_config()
        while not self._shutting_down:
            await asyncio.sleep(max(cfg.idle_worker_timeout_s / 2, 0.5))
            now = time.monotonic()
            idle = [w for w in self.workers.values()
                    if w.state == "IDLE" and now - w.idle_since > cfg.idle_worker_timeout_s]
            # Keep a small warm pool; reap the rest (reference:
            # idle_worker_killing_time_threshold_ms).
            keep = int(self.total.get("CPU"))
            n_idle = sum(1 for w in self.workers.values() if w.state == "IDLE")
            for w in idle:
                if n_idle <= keep:
                    break
                # A worker that owns live objects (in-process store non-empty)
                # must not be reaped: borrowers would lose the data (the
                # reference keeps object data in node-level plasma precisely so
                # worker exit doesn't destroy it; our inline small objects live
                # with their owner).
                try:
                    client = self.worker_clients.get(w.address)
                    owned = await client.call("owned_object_count",
                                              _timeout=2.0)
                except Exception:
                    # Fail closed on transient probe errors, but escalate: a
                    # worker whose RPC channel is wedged for 3 consecutive
                    # probes with no lease is dead weight — reap it.
                    w.probe_failures = getattr(w, "probe_failures", 0) + 1
                    if w.probe_failures < 3 or w.state != "IDLE":
                        continue
                    owned = 0
                else:
                    w.probe_failures = 0
                if owned:
                    continue
                # Re-check after the await: the worker may have been leased
                # while the probe was in flight.
                if w.state != "IDLE":
                    continue
                # DRAINING before the async kill so the lease path cannot
                # hand work to a dying worker mid-kill.
                w.state = "DRAINING"
                await self._kill_worker_proc(w)
                n_idle -= 1

    # ----------------------------------------------------------- worker pool

    async def _spawn_worker(self, is_actor: bool = False,
                            runtime_env: Optional[dict] = None
                            ) -> WorkerHandle:
        from .runtime_env import (conda_env_hash, materialize_conda_env,
                                  materialize_pip_env, pip_env_hash,
                                  worker_env_hash)
        env_hash = worker_env_hash(runtime_env)
        python_exe = sys.executable
        if pip_env_hash(runtime_env) is not None:
            # Build (or reuse) the env's venv off-loop — pip takes seconds —
            # and launch the worker under its interpreter so the task sees
            # the env's package versions, isolated from every other env
            # (reference: _private/runtime_env/pip.py + worker startup).
            from .common import RuntimeEnvSetupError
            try:
                python_exe = await asyncio.get_event_loop().run_in_executor(
                    None, materialize_pip_env, self.session_dir, runtime_env)
            except Exception as e:
                raise RuntimeEnvSetupError(str(e)) from e
        elif conda_env_hash(runtime_env) is not None:
            # Same off-loop materialization for conda (reference:
            # _private/runtime_env/conda.py) — workers launch under the
            # conda env's interpreter, pooled per spec hash.
            from .common import RuntimeEnvSetupError
            try:
                python_exe = await asyncio.get_event_loop().run_in_executor(
                    None, materialize_conda_env, self.session_dir,
                    runtime_env)
            except Exception as e:
                raise RuntimeEnvSetupError(str(e)) from e
        worker_id = WorkerID.from_random().hex()
        env = dict(os.environ)
        env.update(self.worker_env)
        # Ensure spawned workers can import ray_tpu regardless of their cwd.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env.update({
            "RAYTPU_GCS_ADDRESS": self.gcs_address,
            "RAYTPU_AGENT_ADDRESS": self.server.address,
            "RAYTPU_NODE_ID": self.node_id.hex(),
            "RAYTPU_WORKER_ID": worker_id,
            "RAYTPU_CONFIG_JSON": get_config().to_json(),
            "RAYTPU_SESSION_DIR": self.session_dir,
        })
        container = (runtime_env or {}).get("container")
        container_ref = None
        if container:
            # Container isolation (reference: runtime_env/container.py):
            # the worker runs inside `podman/docker run` sharing host
            # network, IPC + /dev/shm (object store), session dir, and the
            # framework source read-only.  The argv builds BEFORE the log
            # file opens so a missing-runtime error leaks no fd.
            from .common import RuntimeEnvSetupError
            from .runtime_env import container_worker_argv
            cname = f"raytpu-{worker_id[:12]}"
            try:
                argv = container_worker_argv(
                    container, self.session_dir, pkg_root, env,
                    passthrough=set(self.worker_env), name=cname)
            except Exception as e:  # noqa: BLE001 — deterministic config
                raise RuntimeEnvSetupError(str(e)) from e
            container_ref = (argv[0], cname)
        log = os.path.join(self.session_dir, "logs", f"worker-{worker_id[:12]}.log")
        logf = open(log, "ab", buffering=0)
        if container:
            proc = await asyncio.create_subprocess_exec(
                *argv, stdout=logf, stderr=logf, env=env)
        else:
            proc = await asyncio.create_subprocess_exec(
                python_exe, "-m", "ray_tpu.core.worker_main",
                stdout=logf, stderr=logf, env=env)
        w = WorkerHandle(worker_id=worker_id, proc=proc, pid=proc.pid,
                         is_actor=is_actor, env_hash=env_hash)
        w.container_ref = container_ref
        self.workers[worker_id] = w
        asyncio.ensure_future(self._monitor_worker(w))
        return w

    async def _monitor_worker(self, w: WorkerHandle):
        if w.proc is None:
            return
        await w.proc.wait()
        await self._on_worker_exit(w, f"worker process exited with code {w.proc.returncode}")

    async def _on_worker_exit(self, w: WorkerHandle, reason: str):
        if w.state == "DEAD":
            return
        prev_state = w.state
        w.state = "DEAD"
        self.workers.pop(w.worker_id, None)
        # drop the dead worker's pushed metric snapshot: under worker
        # churn the per-reporter map would otherwise keep one stale
        # registry copy per dead worker forever (every scrape re-renders
        # them as live series)
        if hasattr(self, "_metrics"):
            self._metrics.pop(f"worker-{w.worker_id[:12]}", None)
        await self._drain_read_pins(w.address)
        # Wake any _grant_lease waiter parked on registration (a worker that
        # crashes during boot must fail the grant now, not after the full
        # register timeout) — same handshake as _kill_worker_proc.
        w.registered.set()
        if prev_state == "LEASED" and w.lease_id and not w.is_actor:
            if w.blocked:  # resources were already released at block time
                self._lease_resources.pop(w.lease_id, None)
            else:
                self._release_lease_resources(w.lease_id)
        if w.is_actor and w.actor_id and not self._shutting_down:
            try:
                # retried + idempotency token: a lost reply must not burn
                # TWO restarts for one death
                if w.intended_exit:
                    # exit_actor(): the worker announced the exit before
                    # dying — even if its own GCS report was lost, this
                    # backstop must not trigger a restart
                    await self.gcs.call_retry(
                        "report_actor_death", actor_id=w.actor_id,
                        reason="exit_actor() (intended)", expected=True)
                else:
                    await self.gcs.call_retry("report_actor_death",
                                              actor_id=w.actor_id,
                                              reason=reason)
            except Exception:
                pass
            if w.lease_id:
                if w.blocked:
                    self._lease_resources.pop(w.lease_id, None)
                else:
                    self._release_lease_resources(w.lease_id)
        await self._process_lease_queue()

    async def _kill_worker_proc(self, w: WorkerHandle):
        was_dead = w.state == "DEAD"
        w.state = "DEAD"
        self.workers.pop(w.worker_id, None)
        if hasattr(self, "_metrics"):  # see _on_worker_exit
            self._metrics.pop(f"worker-{w.worker_id[:12]}", None)
        if not was_dead:
            await self._drain_read_pins(w.address)
        # Release any lease the victim held (kill paths bypass _on_worker_exit,
        # which early-returns once the state is DEAD).
        if not was_dead and w.lease_id:
            if w.blocked:
                w.blocked = False
                self._lease_resources.pop(w.lease_id, None)
                self._bundle_of_lease.pop(w.lease_id, None)
            else:
                self._release_lease_resources(w.lease_id)
            w.lease_id = None
        if w.container_ref is not None:
            # SIGKILLing the podman/docker CLIENT leaves the container (and
            # the worker inside it) running; remove it by name.
            runtime, cname = w.container_ref
            try:
                await asyncio.create_subprocess_exec(
                    runtime, "rm", "-f", cname,
                    stdout=asyncio.subprocess.DEVNULL,
                    stderr=asyncio.subprocess.DEVNULL)
            except Exception:
                pass
        if w.proc is not None:
            try:
                w.proc.kill()
            except ProcessLookupError:
                pass
        # Wake any _grant_lease waiter parked on registration: the grant
        # must fail NOW (state is DEAD), not after the register timeout.
        w.registered.set()
        if not was_dead and not self._shutting_down:
            await self._process_lease_queue()

    async def handle_register_worker(self, worker_id: str, address: str, pid: int):
        w = self.workers.get(worker_id)
        if w is None:
            return {"shutdown": True}
        w.address = address
        w.pid = pid
        if w.state == "STARTING":
            w.state = "IDLE"
            w.idle_since = time.monotonic()
            self._mark_idle_ready(w)
        w.registered.set()
        if self._chaos_runtime_applied:
            # a runtime chaos_set happened before this worker existed: its
            # serialized config predates the spec, so hand it over now
            try:
                await self.worker_clients.get(address).notify(
                    "chaos_update", spec=self._chaos_runtime_spec)
            except Exception:
                pass
        await self._process_lease_queue()
        return {"node_id": self.node_id.hex(), "store_name": self.store.name}

    # --------------------------------------------------------------- leases

    @property
    def _lease_resources(self) -> Dict[str, Dict[str, float]]:
        if not hasattr(self, "_lease_res_map"):
            self._lease_res_map: Dict[str, Dict[str, float]] = {}
        return self._lease_res_map

    def _next_lease_id(self) -> str:
        self._lease_counter += 1
        return f"{self.node_id.hex()[:8]}-{self._lease_counter}"

    def _note_backpressure(self, reason: str):
        """Count a backpressure-rejected lease request (reason: "depth" =
        lease queue at its bound, "draining" = preemption notice)."""
        self._bp_rejects[reason] = self._bp_rejects.get(reason, 0) + 1
        c = sched_explain.backpressure_counter()
        if c is not None:
            key = self._bp_keys.get(reason)
            if key is None:
                key = self._bp_keys[reason] = (
                    ("node", self.node_id.hex()[:12]), ("reason", reason))
            c.inc_key(key)

    def _resource_pool_for(self, bundle: Optional[Tuple[str, int]]) -> ResourceSet:
        if bundle is not None:
            rs = self.bundles.get(tuple(bundle))
            if rs is None:
                raise ValueError(f"unknown placement bundle {bundle}")
            return rs
        return self.available

    async def handle_request_worker_lease(self, resources: Dict[str, float],
                                          bundle: Optional[Tuple[str, int]] = None,
                                          runtime_env: Optional[dict] = None,
                                          allow_spillback: bool = True,
                                          owner: Optional[str] = None,
                                          task_label: str = "",
                                          _writer=None):
        """Grant {worker_address, worker_id, lease_id} | {spillback: node} | queue.

        Grants are tied to the REQUESTING CONNECTION: a grant that
        completes after the requester's connection died is undeliverable —
        returning it as a reply would vanish into a closed socket while
        the lease pins the node's resources forever.  Reclaim the worker
        and raise instead; the error lands in the idempotency cache, so a
        same-token retry re-requests cleanly (and a requester that truly
        gave up leaks nothing)."""
        grant = await self._request_worker_lease(
            resources, bundle, runtime_env, allow_spillback, owner,
            task_label, _writer)
        if (_writer is not None and _writer.is_closing()
                and isinstance(grant, dict) and "lease_id" in grant):
            await self.handle_return_worker_lease(
                grant["lease_id"], grant["worker_id"], worker_alive=True)
            # TransientServerError: dropped from the dedup cache, so a
            # same-token retry on a LIVE connection re-executes and gets a
            # fresh grant instead of replaying this stale error
            raise TransientServerError(
                "lease grant undeliverable: requester connection closed")
        return grant

    handle_request_worker_lease.rpc_pass_writer = True

    async def handle_request_worker_leases(self, count: int,
                                           resources: Dict[str, float],
                                           bundle: Optional[Tuple[str, int]] = None,
                                           runtime_env: Optional[dict] = None,
                                           allow_spillback: bool = True,
                                           owner: Optional[str] = None,
                                           task_label: str = "",
                                           _writer=None):
        """Batched lease grant: up to ``count`` workers in ONE round trip.

        -> {"grants": [grant, ...]} | {"spillback": ...} | {"infeasible": ...}

        The fast path reserves each slot's resources SYNCHRONOUSLY (no
        await between the can_fit check and the acquire), then finishes the
        grants concurrently — a cold batch spawns its workers in parallel
        exactly like ``count`` independent lease RPCs used to, minus the
        per-lease round trips.  When nothing is grantable right now the
        request degrades to the single-lease slow path (queue park /
        spillback / infeasible), preserving those semantics unchanged."""
        count = max(1, int(count))
        if self._draining:
            self._note_backpressure("draining")
            return {"backpressure": True,
                    "retry_after_s": get_config().lease_backpressure_retry_s}
        pending = []
        pool = self._resource_pool_for(bundle)  # ValueError surfaces as-is
        feasible = (bundle is not None
                    or ResourceSet(self.total.to_dict()).can_fit(resources))
        if feasible:
            while len(pending) < count and pool.can_fit(resources):
                pool.acquire(resources)
                pending.append(self._grant_lease(
                    resources, bundle, runtime_env, owner=owner,
                    task_label=task_label, pre_acquired=True))
        if pending:
            out = await asyncio.gather(*pending, return_exceptions=True)
            grants = [g for g in out if isinstance(g, dict)]
            errors = [g for g in out if not isinstance(g, dict)]
            if not grants:
                raise errors[0]
            if errors:
                # Partial failure with partial success: the reply can only
                # carry the grants, but the cause must not vanish — the
                # owner reads a short grant list as "saturated" and simply
                # re-requests, so this log line is the ONLY place a
                # recurring spawn/register failure surfaces.
                try:
                    print(f"[node-agent] {len(errors)}/{len(out)} lease "
                          f"grants in a batch failed: {errors[0]!r}",
                          flush=True)
                except Exception:
                    pass
            if _writer is not None and _writer.is_closing():
                # undeliverable (same contract as the single-lease handler):
                # reclaim every granted worker and let a same-token retry
                # on a live connection re-execute
                for g in grants:
                    await self.handle_return_worker_lease(
                        g["lease_id"], g["worker_id"], worker_alive=True)
                raise TransientServerError(
                    "lease grant undeliverable: requester connection closed")
            return {"grants": grants}
        g = await self.handle_request_worker_lease(
            resources, bundle=bundle, runtime_env=runtime_env,
            allow_spillback=allow_spillback, owner=owner,
            task_label=task_label, _writer=_writer)
        if isinstance(g, dict) and "worker_address" in g:
            return {"grants": [g]}
        return g

    handle_request_worker_leases.rpc_pass_writer = True

    async def _request_worker_lease(self, resources, bundle, runtime_env,
                                    allow_spillback, owner, task_label,
                                    writer=None):
        if self._draining:
            # preemption notice received: stop accepting work — the owner
            # folds this into node re-picking exactly like depth-bound
            # backpressure, and the GCS view's draining flag keeps fresh
            # picks away
            self._note_backpressure("draining")
            return {"backpressure": True,
                    "retry_after_s": get_config().lease_backpressure_retry_s}
        pool = self._resource_pool_for(bundle)
        if bundle is None and not ResourceSet(self.total.to_dict()).can_fit(resources):
            return {"infeasible": True}
        if pool.can_fit(resources):
            return await self._grant_lease(resources, bundle, runtime_env,
                                           owner=owner, task_label=task_label)
        # Saturated: spill to a node that can run it now (reference spillback).
        spill = self._spillback_target(resources) if (allow_spillback and
                                                      bundle is None) else None
        if spill is not None:
            return spill
        cfg = get_config()
        if (cfg.lease_queue_max_depth > 0
                and len(self.lease_queue) >= cfg.lease_queue_max_depth):
            # Lease-queue admission control: parking past the depth bound
            # would grow agent memory without bound under a million-task
            # burst (every parked request pins a future + writer ref).
            # Tell the owner to back off and re-route instead.
            self._note_backpressure("depth")
            return {"backpressure": True,
                    "retry_after_s": cfg.lease_backpressure_retry_s}
        fut = asyncio.get_event_loop().create_future()
        req = LeaseRequest(self._next_lease_id(), resources,
                           tuple(bundle) if bundle else None, fut, runtime_env,
                           allow_spillback=allow_spillback,
                           owner=owner, task_label=task_label,
                           writer=writer)
        self.lease_queue.append(req)
        return await fut

    async def on_disconnect(self, peer, writer):
        """A client connection died: fail its queued lease requests NOW.
        Leaving them queued would eventually grant workers to a requester
        that cannot hear the reply — each such grant permanently leaks a
        slice of this node's capacity (the wedge the chaos harness hits
        within seconds at a 5% frame-drop rate)."""
        stale = [r for r in self.lease_queue if r.writer is writer]
        for req in stale:
            self.lease_queue.remove(req)
            if not req.future.done():
                req.future.set_exception(TransientServerError(
                    "requester disconnected before lease grant"))

    def _spillback_target(self, resources: Dict[str, float]) -> Optional[dict]:
        others = {nid: v for nid, v in self.cluster_view.items()
                  if nid != self.node_id.hex()}
        target = pick_node(others, resources, "DEFAULT")
        if target is not None and others[target].can_fit_now(resources):
            return {"spillback": {"node_id": target,
                                  "address": others[target].address}}
        return None

    async def _grant_lease(self, resources, bundle, runtime_env,
                           owner: Optional[str] = None,
                           task_label: str = "",
                           pre_acquired: bool = False) -> dict:
        from .runtime_env import worker_env_hash
        pool = self._resource_pool_for(bundle)
        if not pre_acquired:
            # batched grants reserve synchronously BEFORE their coroutines
            # interleave (see handle_request_worker_leases) so concurrent
            # slots cannot over-commit the pool
            pool.acquire(resources)
        lease_id = self._next_lease_id()
        if bundle is None:
            self._lease_resources[lease_id] = dict(resources)
        else:
            self._lease_resources[lease_id] = {}
            self._bundle_of_lease[lease_id] = (tuple(bundle), dict(resources))
        env_hash = worker_env_hash(runtime_env)
        w = self._pop_idle_worker(env_hash)
        if w is None:
            try:
                w = await self._spawn_worker(runtime_env=runtime_env)
            except Exception:
                # env materialization / spawn failed: the acquired resources
                # must go back or the node bleeds capacity on every retry
                self._release_lease_resources(lease_id)
                raise
        w.state = "LEASED"
        w.leased_at = time.monotonic()
        w.lease_id = lease_id
        w.owner = owner
        w.task_label = task_label
        try:
            await asyncio.wait_for(w.registered.wait(),
                                   get_config().worker_register_timeout_s)
        except asyncio.TimeoutError:
            await self._kill_worker_proc(w)  # releases the lease resources
            raise RuntimeError("worker failed to register in time")
        if w.state == "DEAD":
            # A kill path (drain, node stop) reaped this worker while it was
            # booting and set the event to wake us; the kill already released
            # the lease resources.  Fail fast so the owner retries at once.
            raise RuntimeError("worker was killed before registering")
        return {"worker_address": w.address, "worker_id": w.worker_id,
                "lease_id": lease_id, "node_id": self.node_id.hex()}

    @property
    def _bundle_of_lease(self) -> Dict[str, Tuple[Tuple[str, int], Dict[str, float]]]:
        if not hasattr(self, "_bundle_lease_map"):
            self._bundle_lease_map = {}
        return self._bundle_lease_map

    def _release_lease_resources(self, lease_id: str):
        if lease_id in self._bundle_of_lease:
            bundle, res = self._bundle_of_lease.pop(lease_id)
            rs = self.bundles.get(bundle)
            if rs is not None:
                rs.release(res)
        else:
            self.available.release(self._lease_resources.get(lease_id, {}))
        self._lease_resources.pop(lease_id, None)

    def _mark_idle_ready(self, w: WorkerHandle):
        """Push a worker that just became IDLE onto the O(1) ready stack
        (MRU at the right — the most recently idled worker has the warmest
        caches and is popped first)."""
        self._idle_ready.setdefault(w.env_hash, collections.deque()) \
            .append(w.worker_id)

    def _pop_idle_worker(self, env_hash: Optional[str] = None
                         ) -> Optional[WorkerHandle]:
        # Fast path: pop from the per-env ready stack, skipping stale
        # entries (workers that died or were leased through another path).
        dq = self._idle_ready.get(env_hash)
        while dq:
            w = self.workers.get(dq.pop())
            if w is not None and w.state == "IDLE" and w.env_hash == env_hash:
                return w
        # Fallback scan: catches IDLE workers that reached the state
        # without passing _mark_idle_ready.
        best = None
        for w in self.workers.values():
            if w.state == "IDLE" and w.env_hash == env_hash:
                if best is None or w.idle_since > best.idle_since:
                    best = w  # MRU: keep caches warm
        return best

    async def handle_worker_blocked(self, worker_id: str):
        """A leased worker blocked on get/wait: release its lease resources so
        nested tasks can run on this node (reference: raylet releases CPU for
        blocked workers — local_task_manager dispatch accounting)."""
        w = self.workers.get(worker_id)
        if (w is not None and w.state == "LEASED" and w.lease_id
                and not w.blocked):
            res = self._lease_resources.get(w.lease_id)
            if res:
                w.blocked = True
                self.available.release(res)
                await self._process_lease_queue()
        return True

    async def handle_worker_unblocked(self, worker_id: str):
        w = self.workers.get(worker_id)
        if w is not None and w.blocked:
            w.blocked = False
            res = self._lease_resources.get(w.lease_id or "", {})
            self.available.force_acquire(res)
        return True

    async def handle_worker_intended_exit(self, worker_id: str):
        """A worker announces its coming exit is deliberate (exit_actor):
        the process-exit backstop reports expected=True so no restart is
        burned even if the worker's own GCS report was lost."""
        w = self.workers.get(worker_id)
        if w is not None:
            w.intended_exit = True
        return True

    async def handle_set_resource(self, name: str, capacity: float):
        """Adjust this node's capacity for one resource at runtime
        (reference: ``experimental/dynamic_resources.py`` set_resource —
        capacity 0 deletes the resource).  Available shifts by the same
        delta (it may go transiently negative while leases drain, exactly
        like the reference's resource deletion under load)."""
        name = str(name)
        capacity = float(capacity)
        if capacity < 0:
            raise ValueError("capacity must be >= 0")
        delta = capacity - self.total.get(name)
        self.total.set(name, capacity)
        # ALWAYS shift available by delta — deleting while leases hold the
        # resource must leave available negative so the eventual lease
        # returns settle back to zero, never to phantom capacity.
        self.available.set(name, self.available.get(name) + delta)
        try:
            await self.gcs.call("update_node_resources",
                                node_id=self.node_id.hex(),
                                total=self.total.to_dict(),
                                available=self.available.to_dict())
        except Exception:
            pass  # the next heartbeat carries available; view self-heals
        await self._process_lease_queue()
        return {"total": self.total.to_dict()}

    async def handle_return_worker_lease(self, lease_id: str, worker_id: str,
                                         worker_alive: bool = True):
        # Surface the death cause to the owner: an OOM-killed worker's task
        # should fail with a typed OutOfMemoryError naming the policy, not a
        # generic WorkerCrashedError.
        death_cause = self._oom_kills.pop(worker_id, None)
        w0 = self.workers.get(worker_id)
        if w0 is not None and w0.blocked and w0.lease_id == lease_id:
            # Block already released the resources; just drop the record.
            w0.blocked = False
            self._lease_resources.pop(lease_id, None)
            self._bundle_of_lease.pop(lease_id, None)
        else:
            self._release_lease_resources(lease_id)
        w = self.workers.get(worker_id)
        if w is not None and w.lease_id == lease_id:
            if worker_alive and w.state == "LEASED":
                w.state = "IDLE"
                w.lease_id = None
                w.idle_since = time.monotonic()
                self._mark_idle_ready(w)
            elif not worker_alive:
                await self._kill_worker_proc(w)
        await self._process_lease_queue()
        return {"ok": True, "death_cause": death_cause}

    async def _process_lease_queue(self):
        i = 0
        while i < len(self.lease_queue):
            req = self.lease_queue[i]
            if req.writer is not None and req.writer.is_closing():
                # requester's connection died while queued (see
                # on_disconnect; this catches the race where the writer
                # closed without the disconnect callback yet): granting
                # would leak the worker
                self.lease_queue.pop(i)
                if not req.future.done():
                    req.future.set_exception(TransientServerError(
                        "requester disconnected before lease grant"))
                continue
            try:
                pool = self._resource_pool_for(req.bundle)
            except ValueError:
                self.lease_queue.pop(i)
                if not req.future.done():
                    req.future.set_exception(ValueError(f"bundle {req.bundle} removed"))
                continue
            if req.bundle is None and not ResourceSet(
                    self.total.to_dict()).can_fit(req.resources):
                # capacity shrank below the demand after admission
                # (dynamic set_resource): answer infeasible NOW — same
                # response the admission check would give a fresh request —
                # so the owner re-routes instead of waiting forever.
                self.lease_queue.pop(i)
                if not req.future.done():
                    req.future.set_result({"infeasible": True})
                continue
            if pool.can_fit(req.resources):
                self.lease_queue.pop(i)
                try:
                    grant = await self._grant_lease(req.resources, req.bundle,
                                                    req.runtime_env,
                                                    owner=req.owner,
                                                    task_label=req.task_label)
                    if not req.future.done():
                        req.future.set_result(grant)
                except Exception as e:  # noqa: BLE001
                    if not req.future.done():
                        req.future.set_exception(e)
                continue
            # Re-evaluate spillback for queued requests: the cluster view may
            # have been stale (or other nodes freed up) since the request was
            # queued (reference: ClusterTaskManager retries spillback on each
            # scheduling pass).
            if req.allow_spillback and req.bundle is None:
                spill = self._spillback_target(req.resources)
                if spill is not None:
                    self.lease_queue.pop(i)
                    if not req.future.done():
                        req.future.set_result(spill)
                    continue
            i += 1

    async def handle_node_stacks(self) -> Dict[str, str]:
        """Stack dumps of every registered worker on this node plus the
        agent itself (reference: dashboard/modules/reporter stack traces)."""
        from ray_tpu.util.debug import dump_all_stacks
        out: Dict[str, str] = {}
        out["agent"] = dump_all_stacks()
        for w in list(self.workers.values()):
            if not w.address:
                continue
            try:
                out[f"worker-{w.worker_id[:12]}"] = await self.worker_clients \
                    .get(w.address).call("dump_stacks", _timeout=5.0)
            except Exception as e:  # noqa: BLE001
                out[f"worker-{w.worker_id[:12]}"] = f"<unavailable: {e}>"
        return out

    async def handle_profile(self, duration_s: float = 2.0,
                             worker_id: Optional[str] = None):
        """On-demand profiler capture on this node (``raytpu profile
        --node <id> --duration <s>``): forwards to a registered worker —
        that's the process holding the jax/TPU backend, so a TPU worker
        answers with a ``jax.profiler.trace`` directory and a CPU worker
        with sampled thread stacks as chrome-trace JSON.  LEASED workers
        are preferred (the train/serve step is what the operator wants to
        see); a node with no reachable worker profiles the agent itself.
        Returns {"path", "mode", "process"} — the artifact lands under
        the node's session dir."""
        out_dir = os.path.join(self.session_dir, "profiles")
        candidates = [w for w in self.workers.values()
                      if w.address and (worker_id is None
                                        or w.worker_id.startswith(worker_id))]
        candidates.sort(key=lambda w: w.state != "LEASED")
        for w in candidates[:3]:
            try:
                return await self.worker_clients.get(w.address).call(
                    "profile", duration_s=duration_s, out_dir=out_dir,
                    _timeout=duration_s + 30.0)
            except Exception:
                continue
        from ray_tpu.util import profiler
        loop = asyncio.get_event_loop()
        path, mode = await loop.run_in_executor(
            None, lambda: profiler.capture(duration_s, out_dir))
        return {"path": path, "mode": mode, "process": "agent"}

    async def handle_kill_worker(self, worker_id: str, reason: str = ""):
        w = self.workers.get(worker_id)
        if w is None:
            return False
        await self._kill_worker_proc(w)
        return True

    # ---------------------------------------------------------------- chaos

    async def handle_chaos_update(self, spec: Optional[dict],
                                  version: int | None = None):
        """Runtime chaos control reached this node (GCS chaos_set via
        pubsub/heartbeat, or a direct call): install the spec locally,
        re-arm the kill schedule, and forward to every registered worker."""
        await self._apply_chaos(spec, version)
        return True

    async def _apply_chaos(self, spec: Optional[dict],
                           version: int | None = None):
        chaos.install(spec)
        self._chaos_runtime_spec = spec
        self._chaos_runtime_applied = True
        if version is not None:
            self._chaos_version = version
        self._arm_chaos_schedule()
        for w in list(self.workers.values()):
            if not w.address:
                continue
            try:
                await self.worker_clients.get(w.address).notify(
                    "chaos_update", spec=spec)
            except Exception:
                pass

    def _arm_chaos_schedule(self):
        """(Re)start the seeded kill-schedule loop for the installed
        injector (the NodeKillerActor analogue, reference:
        test_utils.py:1401 — here at worker granularity: agent/node kills
        stay with Cluster.kill_node)."""
        if self._chaos_kill_task is not None:
            self._chaos_kill_task.cancel()
            self._chaos_kill_task = None
        inj = chaos.injector()
        if inj is None or not inj.kills:
            return
        self._chaos_kill_task = asyncio.ensure_future(
            self._chaos_kill_loop(inj))

    async def _chaos_kill_loop(self, inj):
        t0 = time.monotonic()
        my_id = self.node_id.hex()
        for entry in sorted(inj.kills, key=lambda k: float(k.get("after_s", 0))):
            node_sel = entry.get("node")
            if node_sel and not my_id.startswith(str(node_sel)):
                continue
            kind = entry.get("kind") or entry.get("target", "worker")
            if kind not in ("worker", "preempt_node", "node"):
                continue
            delay = t0 + float(entry.get("after_s", 0)) - time.monotonic()
            if delay > 0:
                await asyncio.sleep(delay)
            if kind in ("preempt_node", "node"):
                # Seeded node preemption: deliver the shutdown notice to
                # OURSELVES — notice_s>0 exercises the graceful drain,
                # notice_s=0 the no-warning hard kill.  This agent is
                # going away; stop walking the schedule.
                if self._shutting_down:
                    return
                inj.record("preempt_node")
                self._begin_preemption(float(entry.get("notice_s", 0.0)))
                return
            # A scheduled kill with no victim yet (workers still booting)
            # waits briefly so "1 scheduled kill" reliably means 1 kill.
            victim = None
            for _ in range(100):
                if self._shutting_down:
                    return
                victim = self._pick_chaos_victim()
                if victim is not None:
                    break
                await asyncio.sleep(0.1)
            if victim is None:
                continue
            inj.record("worker_kill")
            try:
                print(f"[chaos] killing worker {victim.worker_id[:12]} "
                      f"(seeded schedule, node {my_id[:12]})", flush=True)
            except Exception:
                pass
            await self._kill_worker_proc(victim)

    def _pick_chaos_victim(self):
        """Deterministic victim: the first registered NON-ACTOR worker by
        worker id (leased preferred — killing it exercises the task-retry
        path; actors are spared so a kill never burns an actor restart
        the workload did not budget for)."""
        live = sorted((w for w in self.workers.values()
                       if w.registered.is_set() and not w.is_actor
                       and w.state in ("IDLE", "LEASED")),
                      key=lambda w: w.worker_id)
        leased = [w for w in live if w.state == "LEASED"]
        pool = leased or live
        return pool[0] if pool else None

    # ----------------------------------------------------- preemption drain

    async def handle_drain_self(self, notice_s: float = 0.0):
        """Deliver a preemption notice to this node (the cloud provider's
        shutdown warning, an operator drain, or the chaos plane's seeded
        ``preempt_node``).  ``notice_s > 0`` drains gracefully — stop
        accepting leases, re-home sole-copy objects, let outstanding
        leases return — with a HARD cutoff when the notice expires;
        ``notice_s = 0`` is the no-warning preemption (the node just
        disappears, recovery rides the external tier + lineage)."""
        self._begin_preemption(notice_s)
        return True

    def _begin_preemption(self, notice_s: float):
        if self._preempt_task is not None or self._shutting_down:
            return
        self._preempt_task = asyncio.ensure_future(self._preempt(notice_s))

    async def _preempt(self, notice_s: float):
        notice_s = max(0.0, float(notice_s))
        try:
            print(f"[preempt] node {self.node_id.hex()[:12]}: preemption "
                  f"notice, {notice_s:.1f}s to drain", flush=True)
        except Exception:
            pass
        if notice_s <= 0:
            await self._preempt_finish(graceful=False)
            return
        self._draining = True
        deadline = time.monotonic() + notice_s
        # tell the GCS at drain START (not the end): the notice is the
        # elastic train plane's advance warning — a trainer with workers
        # here resizes DOWN inside the notice window instead of eating an
        # actor death.  Best-effort: a lost report just means the slower
        # heartbeat-draining path carries the flag.
        try:
            await asyncio.wait_for(
                self.gcs.call("report_drain_notice",
                              node_id=self.node_id.hex(),
                              notice_s=notice_s),
                timeout=min(2.0, notice_s / 2))
        except Exception:
            pass
        # shed queued lease requests NOW: every parked owner re-picks a
        # node instead of waiting on a grant that will never come
        cfg = get_config()
        for req in list(self.lease_queue):
            self.lease_queue.remove(req)
            if not req.future.done():
                self._note_backpressure("draining")
                req.future.set_result(
                    {"backpressure": True,
                     "retry_after_s": cfg.lease_backpressure_retry_s})
        try:
            await asyncio.wait_for(
                self._drain_objects(deadline),
                max(0.05, deadline - time.monotonic()))
        except asyncio.TimeoutError:
            pass
        except Exception:
            pass
        # flush: an evict-triggered external spill may still be in flight
        # on the writer thread, and its owner registration only fires
        # after the write lands — exiting now would kill the sole copy
        # mid-upload (or leave it durable but unfindable)
        try:
            await asyncio.wait_for(
                self._flush_external_writes(deadline),
                max(0.05, deadline - time.monotonic()))
        except (asyncio.TimeoutError, Exception):
            pass
        # let outstanding leases return on their own, up to the deadline
        while (time.monotonic() < deadline
               and any(w.state == "LEASED" for w in self.workers.values())):
            await asyncio.sleep(0.05)
        await self._preempt_finish(graceful=True)

    async def _flush_external_writes(self, deadline: float):
        """Wait out in-flight external spill writes AND the pending
        owner-registration tasks they trigger (the write-done callback
        marshals the registration onto this loop via
        ``call_soon_threadsafe``, so one extra tick must pass before the
        ``_loc_updates`` task even exists)."""
        loop = asyncio.get_event_loop()
        for fut in list(self.store._ext_writes.values()):
            left = deadline - time.monotonic()
            if left <= 0:
                return
            try:
                await loop.run_in_executor(
                    None, lambda f=fut, t=left: f.result(max(0.1, t)))
            except Exception:
                pass
        await asyncio.sleep(0.05)  # let threadsafe-scheduled callbacks land
        for t in list(self._loc_updates.values()):
            left = deadline - time.monotonic()
            if left <= 0:
                return
            try:
                await asyncio.wait_for(asyncio.shield(t), left)
            except Exception:
                pass

    async def _drain_objects(self, deadline: float):
        """Re-home the owner-known sealed objects this node holds before
        it disappears — BOTH in-store entries and locally-spilled files (a
        local .spill file is just as much a sole copy as a shm entry):
        write-once to the external tier when configured (and register the
        URI with the owner as a non-node location), else replicate to a
        live peer.  Objects already on the external tier are skipped —
        they are durable already."""
        my_id = self.node_id.hex()
        peers = [v for nid, v in self.cluster_view.items()
                 if nid != my_id and v.alive
                 and not getattr(v, "draining", False)]
        loop = asyncio.get_event_loop()

        def _read_spill(path):
            with open(path, "rb") as f:
                return f.read()

        # Only OWNER-KNOWN objects re-home: an ownerless upload could never
        # be registered with anyone (undiscoverable) and nothing would
        # ever delete it — a permanent tier leak.  Ownership tracks
        # primariness by construction: task results / puts carry the owner
        # through store_create, while copies this node PULLED do not — so
        # the drain spends its notice window on the copies only this node
        # has, not on re-uploading a broadcast's replicas.
        victims = [(oid, e.owner, None)
                   for oid, e in list(self.store._entries.items())
                   if e.sealed and not e.freed and e.owner]
        victims += [(oid, self.store._spilled_owners[oid], path)
                    for oid, path in list(self.store._spilled.items())
                    if oid in self.store._spilled_owners]
        for oid, owner, spill_path in victims:
            if time.monotonic() >= deadline:
                return
            if oid in self.store._spilled_external:
                continue
            try:
                if spill_path is not None:
                    data = await loop.run_in_executor(None, _read_spill,
                                                      spill_path)
                else:
                    # [:size]: a seal-truncated entry's segment is the
                    # larger reservation; the tail is not data
                    ent = self.store._entries[oid]
                    data = bytes(ent.segment.view()[:ent.size])
            except Exception:
                continue
            if self.store.external_uri:
                uri = external_spill.object_uri(self.store.external_uri, oid)
                try:
                    await loop.run_in_executor(
                        None, external_spill.write, uri, data)
                except Exception:
                    continue
                self.store._spilled_external[oid] = uri
                self.store._ext_sizes[oid] = len(data)
                m = external_spill.spill_metrics()
                if m is not None:
                    m["bytes"].inc_key(external_spill.KEY_TIER_EXTERNAL,
                                       len(data))
                object_explain.ledger_record(object_explain.KEY_RE_HOME,
                                             len(data))
                self._obj_event(oid, object_explain.ObjectEvent.RE_HOMED,
                                to=uri, tier="external", size=len(data))
                if owner:
                    # awaited (not the background _location_update): the
                    # registration must land before this node dies or the
                    # copy is durable but unfindable
                    try:
                        await self.worker_clients.get(owner).call_retry(
                            "add_object_location", object_id=oid,
                            node_id=EXTERNAL_NODE_ID, address=uri,
                            _timeout=10.0)
                    except Exception:
                        pass
            else:
                # no external tier: replicate to the first peer that will
                # take it (one full/slow peer must not drop the rest of
                # the objects when others have room)
                for peer in peers:
                    if time.monotonic() >= deadline:
                        return
                    try:
                        await self.agent_clients.get(
                            peer.address).call_retry(
                            "store_put", object_id=oid, data=data,
                            owner=owner, _timeout=30.0)
                    except Exception:
                        continue
                    object_explain.ledger_record(
                        object_explain.KEY_RE_HOME, len(data))
                    self._obj_event(oid,
                                    object_explain.ObjectEvent.RE_HOMED,
                                    to=peer.address, tier="peer",
                                    size=len(data))
                    if owner:
                        try:
                            await self.worker_clients.get(
                                owner).call_retry(
                                "add_object_location", object_id=oid,
                                node_id=peer.node_id,
                                address=peer.address, _timeout=10.0)
                        except Exception:
                            pass
                    break

    async def _preempt_finish(self, graceful: bool):
        self._draining = True
        if graceful and self.gcs is not None:
            # deregister NOW: actors reschedule and the view stops routing
            # here immediately, instead of waiting out the health-check
            # threshold like an unannounced death
            try:
                await asyncio.wait_for(
                    self.gcs.call("drain_node", node_id=self.node_id.hex()),
                    5.0)
            except Exception:
                pass
        hook = self._on_preempt_exit
        if hook is not None:
            # standalone agent process: the whole "VM" disappears — take
            # the worker subprocesses down with it and exit hard, no
            # orderly unwind (that is what a preemption is)
            for w in list(self.workers.values()):
                if w.proc is not None:
                    try:
                        w.proc.kill()
                    except ProcessLookupError:
                        pass
            hook(graceful)
            return
        await self.stop()

    # --------------------------------------------------------------- actors

    async def handle_create_actor(self, spec: TaskSpec):
        """Lease a dedicated worker and run the actor-creation task on it
        (reference: GcsActorScheduler lease + PushTask of the creation task)."""
        # PG-placed actors lease out of the reserved bundle pool, NOT the free
        # pool — the bundle already holds those resources (prepare/commit), so
        # leasing from the free pool would double-count them.
        strategy = spec.scheduling_strategy
        bundle = None
        if (isinstance(strategy, (tuple, list)) and strategy
                and strategy[0] == "_pg"):
            bundle = (strategy[1], strategy[2])
        grant = await self.handle_request_worker_lease(
            resources=spec.resources, bundle=bundle,
            runtime_env=spec.runtime_env, allow_spillback=False)
        if "worker_address" not in grant:
            raise RuntimeError(f"cannot place actor here: {grant}")
        w = self.workers[grant["worker_id"]]
        w.is_actor = True
        w.actor_id = spec.actor_id.hex()
        client = self.worker_clients.get(grant["worker_address"])
        try:
            # Idempotent retry: a creation reply lost to a flaky link (a
            # chaos drop deterministically hits the FIRST reply of every
            # fresh worker for some seeds) replays from the worker's dedup
            # window instead of failing placement forever.
            await client.call_retry(
                "create_actor", spec=spec,
                _timeout=get_config().actor_creation_timeout_s)
        except Exception:
            await self._kill_worker_proc(w)
            self._release_lease_resources(grant["lease_id"])
            raise
        return {"worker_address": grant["worker_address"],
                "worker_id": grant["worker_id"]}

    # ------------------------------------------------------ placement bundles

    # Single-bundle RPCs: thin wrappers over the batched forms below so the
    # prepare/commit/return semantics live in exactly one place.

    async def handle_prepare_bundle(self, pg_id: str, bundle_index: int,
                                    resources: Dict[str, float]) -> bool:
        return await self.handle_prepare_bundles(
            pg_id, {bundle_index: resources})

    async def handle_commit_bundle(self, pg_id: str, bundle_index: int) -> bool:
        key = (pg_id, bundle_index)
        if key not in self.prepared_bundles and key in self.bundles:
            return True
        if key not in self.prepared_bundles:
            return False
        return await self.handle_commit_bundles(pg_id, [bundle_index])

    async def handle_return_bundle(self, pg_id: str, bundle_index: int) -> bool:
        return await self.handle_return_bundles(pg_id, [bundle_index])

    # Batched bundle RPCs: the GCS PG manager fans out ONE call per node
    # per phase (or a single fused call for single-node placements) instead
    # of one per bundle — the 2-phase protocol is unchanged, only the RPC
    # count drops (reference PrepareBundleResources batches the same way,
    # gcs_placement_group_scheduler.cc).

    def _acquire_all(self, pg_id: str,
                     bundles: Dict[int, Dict[str, float]]) -> bool:
        """All-or-nothing local prepare of several bundles."""
        taken = []
        for idx, resources in bundles.items():
            key = (pg_id, int(idx))
            if key in self.prepared_bundles or key in self.bundles:
                continue
            if not self.available.can_fit(resources):
                for k in taken:
                    self.available.release(self.prepared_bundles.pop(k).to_dict())
                return False
            self.available.acquire(resources)
            self.prepared_bundles[key] = ResourceSet(resources)
            taken.append(key)
        return True

    async def handle_prepare_bundles(self, pg_id: str,
                                     bundles: Dict[int, Dict[str, float]]) -> bool:
        return self._acquire_all(pg_id, bundles)

    async def handle_commit_bundles(self, pg_id: str, indices) -> bool:
        for idx in indices:
            key = (pg_id, int(idx))
            rs = self.prepared_bundles.pop(key, None)
            if rs is not None:
                self.bundles[key] = rs
        return True

    async def handle_prepare_commit_bundles(
            self, pg_id: str, bundles: Dict[int, Dict[str, float]]) -> bool:
        """Fused single-round-trip path: safe when the WHOLE placement is on
        this node (no cross-node atomicity to wait for)."""
        if not self._acquire_all(pg_id, bundles):
            return False
        for idx in bundles:
            key = (pg_id, int(idx))
            rs = self.prepared_bundles.pop(key, None)
            if rs is not None:
                self.bundles[key] = rs
        return True

    async def handle_return_bundles(self, pg_id: str, indices) -> bool:
        for idx in indices:
            key = (pg_id, int(idx))
            rs = (self.prepared_bundles.pop(key, None)
                  or self.bundles.pop(key, None))
            if rs is not None:
                self.available.release(rs.to_dict())
        await self._process_lease_queue()
        return True

    # ----------------------------------------------------------- object store

    async def handle_store_create(self, object_id: ObjectID, size: int,
                                  owner: Optional[str] = None):
        try:
            path = self.store.create(object_id, size, owner=owner)
        except ObjectStoreFullError as e:
            raise e
        return {"path": path}

    async def handle_store_seal(self, object_id: ObjectID,
                                size: Optional[int] = None):
        """``size`` (reserve-then-write puts): the exact byte count
        written — the entry truncates to it so the reservation's slack
        tail never serves, ships, or spills."""
        self.store.seal(object_id, truncate_to=size)
        return True

    async def handle_store_put(self, object_id: ObjectID, data: bytes,
                               owner: Optional[str] = None):
        self.store.create_and_write(object_id, data, owner=owner)
        return {"path": self.store.get_path(object_id)[0]}

    async def handle_store_get(self, object_id: ObjectID,
                               timeout: Optional[float] = 0.0):
        if self.store.external_only(object_id):
            res = await self._restore_external(object_id)
            if res is not None:
                return res
        if not self.store.contains(object_id):
            if not timeout:
                return None
            ok = await self.store.wait_sealed(object_id, timeout)
            if not ok:
                return None
        located = self.store.get_path(object_id)
        if located is None:
            return None  # freed-deferred (sealed but deleted) or evicted
        path, size = located
        return {"path": path, "size": size}

    async def handle_store_verify(self, object_id: ObjectID,
                                  path: str) -> bool:
        """Post-copy read validation for arena-backed objects: True iff the
        object is still sealed AT this path.  Runs on the agent loop — the
        same loop that evicts — so a True answer proves no evict+offset-reuse
        interleaved with the caller's copy (the file-per-object store never
        needed this: an unlinked file cannot alias a new object)."""
        e = self.store._entries.get(object_id)
        if e is not None and e.sealed and not e.freed \
                and e.segment.path == path:
            return True
        # Same-host proxy: the pin we hold on the source's real entry keeps
        # that slice from being evicted (and its offset from being reused)
        # for as long as the proxy exists, so presence-at-path IS validity.
        # A freed-deferred proxy fails verification: its slice outlives only
        # the current pin holders, not this caller's copy.
        p = self.store._proxies.get(object_id)
        if p is not None and not p.freed and p.path == path:
            return True
        # evicted-but-spilled (or restored elsewhere): not at `path` anymore
        return False

    async def handle_object_info(self, object_id: ObjectID):
        """Describe a sealed local object for a prospective puller: same-host
        pullers (matching host_key) zero-copy attach `path` instead of
        pulling bytes (see _pull_object).

        Answers from metadata only — a spilled entry returns None rather
        than being restored from disk just to satisfy a probe from a puller
        that may pick a different source (the byte-pull path restores on
        read_chunk when this node is actually chosen)."""
        # freed-deferred records are deleted, just not yet reclaimed: they
        # must be invisible to prospective pullers (same invariant as
        # contains/get_path/store_verify).
        e = self.store._entries.get(object_id)
        if e is not None and e.sealed and not e.freed:
            return {"path": e.segment.path, "size": e.size,
                    "host_key": self.host_key, "proxy": False}
        if (e is not None and not e.freed and e.avail
                and get_config().object_transfer_partial_serving):
            # in-progress pull publishing its chunk ledger: advertise the
            # held [start, end) ranges so other pullers stripe onto us
            # mid-broadcast.  Not zero-copy attachable (no pin on an
            # unsealed entry) — byte pulls only.
            return {"path": e.segment.path, "size": e.size,
                    "host_key": self.host_key, "proxy": False,
                    "partial": True,
                    "ranges": [list(r) for r in e.avail]}
        p = self.store._proxies.get(object_id)
        if p is not None and not p.freed:
            return {"path": p.path, "size": p.size,
                    "host_key": self.host_key, "proxy": True}
        return None

    async def handle_pin_object(self, object_id: ObjectID) -> bool:
        """Pin a REAL local entry for a same-host proxy holder (proxies can't
        be pinned — the second-level puller falls back to the true origin)."""
        e = self.store._entries.get(object_id)
        if e is None or not e.sealed or e.freed:
            return False
        self.store.pin(object_id)
        return True

    async def handle_unpin_object(self, object_id: ObjectID):
        await self._unpin_and_chain(object_id)

    async def handle_store_unpin_read(self, object_id: ObjectID,
                                      pinner: Optional[str] = None):
        """A consumer's last zero-copy view over ``object_id`` died: drop
        the read pin taken by ``fetch_object(pin=True)``.  May complete a
        deferred free — and for proxies, forward the release to the source
        agent whose slice backed the view.

        A release with no matching ledger record is STALE — the consumer's
        pins were already drained on its death/disconnect and this notify
        was in flight — and must be ignored, not applied: the store counter
        it would decrement now belongs to another consumer's pin."""
        if pinner:
            per = self._read_pins.get(pinner)
            kinds = per.get(object_id) if per is not None else None
            if not kinds:
                return True
            kind = next(iter(kinds))
            kinds[kind] -= 1
            if kinds[kind] <= 0:
                del kinds[kind]
            if not kinds:
                per.pop(object_id, None)
                self._pin_first_ts.pop((pinner, object_id), None)
                if not per:
                    self._read_pins.pop(pinner, None)
            await self._unpin_and_chain(object_id, kind)
        else:
            await self._unpin_and_chain(object_id)
        return True

    async def _pin_sweep_loop(self):
        """Liveness sweep for read-pin holders AND lease owners the worker
        monitor does not cover — chiefly the DRIVER, which is a consumer
        but not a spawned worker.  A consumer that vanishes without its
        exit drain (SIGKILL, preemption, or leases GC'd after the worker's
        shutdown flag suppressed the release notify) would otherwise leave
        its objects pinned — unevictable, frees deferred — for the agent's
        whole lifetime; a dead DRIVER's granted leases would pin this
        node's CPUs forever (the lease return is driver-side, and a
        SIGKILLed driver never sends it — a 2-CPU node fully leased to a
        dead driver can never schedule again).  Every consumer runs an RPC
        server with a ``ping`` handler, so a repeatedly unreachable
        address means the process is gone.  Acting on confirmed death
        only: a TIMEOUT means alive-but-busy, and a single connect failure
        can be transient (fd exhaustion, one dropped pooled connection) —
        releasing a LIVE consumer's pins would let the arena recycle
        slices under its views, so death takes three consecutive failed
        sweeps (~30 s) to declare."""
        strikes: Dict[str, int] = {}
        while not self._shutting_down:
            await asyncio.sleep(10.0)
            managed = {w.address for w in self.workers.values()}
            lease_owners = {w.owner for w in self.workers.values()
                            if w.state == "LEASED" and w.owner
                            and not w.is_actor}
            targets = {a for a in self._read_pins
                       if a not in managed} | lease_owners
            for addr in targets:
                try:
                    await asyncio.wait_for(
                        self.worker_clients.get(addr).call("ping"), 5.0)
                    strikes.pop(addr, None)
                except asyncio.TimeoutError:
                    continue
                except Exception:
                    # drop the pooled (possibly wedged) connection so the
                    # next strike probes with a fresh connect
                    await self.worker_clients.close(addr)
                    strikes[addr] = strikes.get(addr, 0) + 1
                    if strikes[addr] >= 3:
                        strikes.pop(addr, None)
                        if addr in self._read_pins:
                            await self._drain_read_pins(addr)
                        await self._reclaim_dead_owner_leases(addr)
            for a in list(strikes):
                if a not in self._read_pins and a not in lease_owners:
                    strikes.pop(a)

    async def _reclaim_dead_owner_leases(self, owner: str):
        """A lease owner is confirmed dead: kill its leased task workers
        (their results have nowhere to go — the work is orphaned) so the
        lease resources return to the pool.  Actor workers are spared:
        actor lifetime is GCS-managed (job GC / max_restarts), not tied to
        the submitting owner's process."""
        for w in list(self.workers.values()):
            if w.state == "LEASED" and w.owner == owner and not w.is_actor:
                try:
                    print(f"[node-agent] reclaiming lease {w.lease_id} of "
                          f"dead owner {owner}", flush=True)
                except Exception:
                    pass
                await self._kill_worker_proc(w)

    async def _drain_read_pins(self, consumer_addr: Optional[str]):
        """Release every read pin a dead consumer still held (the plasma
        disconnect-releases-pins contract); completes deferred frees."""
        if not consumer_addr:
            return
        for oid, kinds in self._read_pins.pop(consumer_addr, {}).items():
            self._pin_first_ts.pop((consumer_addr, oid), None)
            for kind, count in kinds.items():
                for _ in range(count):
                    await self._unpin_and_chain(oid, kind)

    async def _unpin_and_chain(self, object_id: ObjectID,
                               kind: Optional[str] = None):
        await self._notify_source_unpin(self.store.unpin(object_id, kind),
                                        object_id)

    async def _notify_source_unpin(self, source: Optional[str],
                                   object_id: ObjectID):
        """A completed free of a same-host proxy returns the SOURCE agent's
        address: release the transfer pin we hold on its real entry so the
        origin slice becomes evictable again."""
        if not source:
            return
        try:
            await self.agent_clients.get(source).notify(
                "unpin_object", object_id=object_id)
        except Exception:
            pass

    async def handle_store_free(self, object_ids: List[ObjectID]):
        for oid in object_ids:
            await self._notify_source_unpin(self.store.free(oid), oid)
        return True

    async def handle_store_contains(self, object_id: ObjectID) -> bool:
        return self.store.contains(object_id)

    async def handle_store_stats(self):
        return self.store.stats()

    async def handle_store_objects(self):
        """Per-object refcount/size/location rows for ``raytpu memory``."""
        rows = self.store.objects()
        for r in rows:
            r["node_id"] = self.node_id.hex()
        return rows

    # -------------------------------------- object-plane flight recorder

    def _buffer_object_event(self, object_id: ObjectID, event: str,
                             detail: dict):
        """Store-hook target + agent-originated stamp point: one bounded
        append per lifecycle transition; the flush loop ships batches to
        the GCS object-event ring.  Callers (the store's ``_event`` and
        ``_obj_event`` below) already checked the kill switch."""
        if len(self._object_events) >= 10_000:
            self._object_events_dropped += 1
            return
        self._object_events.append({
            "object_id": object_id.hex(), "event": event,
            "ts": time.time(), "node": self.node_id.hex()[:12], **detail})

    def _obj_event(self, object_id: ObjectID, event: str, **detail):
        """Agent-side transition stamp (pull landings, proxy attaches,
        re-homes, pin grants) — same trail as the store's transitions."""
        if not object_explain.enabled():
            return
        self._buffer_object_event(object_id, event, detail)

    async def _flush_object_events_loop(self):
        while not self._shutting_down:
            await asyncio.sleep(1.0)
            if not self._object_events or self.gcs is None:
                continue
            batch, self._object_events = self._object_events, []
            dropped, self._object_events_dropped = \
                self._object_events_dropped, 0
            try:
                await self.gcs.call_retry("add_object_events",
                                          events=batch, dropped=dropped)
            except Exception:
                pass

    def _record_transfer(self, object_id: ObjectID, size: int, kind: str,
                         t0: float, status: str, source: str = "",
                         stats: Optional[dict] = None):
        """Append one completed/failed pull's end-state to the bounded
        per-agent flight-recorder ring (``state.transfers()``)."""
        if not object_explain.enabled():
            return
        rec = {"object_id": object_id.hex(), "bytes": size, "kind": kind,
               "status": status, "node": self.node_id.hex()[:12],
               "ts": t0, "duration_s": round(time.time() - t0, 6)}
        if source:
            rec["source"] = source
        if stats:
            rec.update(stats)
        self._transfer_ring.append(rec)

    async def handle_transfers(self, limit: int = 100):
        """Tail of this agent's per-pull flight-recorder ring, newest
        first: per-source bytes/chunks/failures, steals, retries, relay
        fraction — the post-hoc answer to "how did this object get
        here"."""
        out = []
        for rec in reversed(self._transfer_ring):
            out.append(rec)
            if len(out) >= max(1, limit):
                break
        return out

    def _leak_suspects_cheap(self, ttl_s: float, now: float) -> list:
        """The probe-free half of the leak report (also sampled into
        ``raytpu_mem_leak_suspects``): read pins held past the TTL by
        consumers the liveness sweep still believes alive, and deferred
        frees stuck behind pins no ledger entry accounts for (the holder
        vanished without a drain — nothing will ever complete the free)."""
        leaks = []
        for (pinner, oid), t0 in list(self._pin_first_ts.items()):
            age = now - t0
            if age < ttl_s:
                continue
            kinds = self._read_pins.get(pinner, {}).get(oid, {})
            leaks.append({"kind": "pin_ttl", "object_id": oid.hex(),
                          "holder": pinner, "age_s": round(age, 1),
                          "pins": sum(kinds.values())})
        # ledger-accounted pin totals per object (read pins only; an
        # in-flight pull legitimately holds an unledgered transfer pin)
        accounted: Dict[ObjectID, int] = {}
        for per in self._read_pins.values():
            for oid, kinds in per.items():
                accounted[oid] = accounted.get(oid, 0) + sum(kinds.values())
        for oid, e in list(self.store._entries.items()):
            if not e.freed or e.pinned <= 0:
                continue
            if oid in self._inflight_pulls:
                continue  # transfer pin: the pull's unpin completes it
            if accounted.get(oid, 0) < e.pinned:
                leaks.append({
                    "kind": "vanished_pin", "object_id": oid.hex(),
                    "pins": e.pinned, "accounted": accounted.get(oid, 0),
                    "age_s": round(time.monotonic() - e.last_access, 1),
                    "size": e.size})
        return leaks

    async def handle_store_leaks(self, pin_ttl_s: Optional[float] = None):
        """Ref-debt / leak report for this node (``raytpu memory
        --leaks``): pin-TTL and vanished-pin suspects from the cheap
        sweep, plus sole-copy entries whose OWNER process no longer
        answers a ping — durable bytes no reachable borrower can ever
        free (the owner-side refcount died with the owner)."""
        ttl = pin_ttl_s if pin_ttl_s is not None \
            else get_config().object_pin_leak_ttl_s
        leaks = self._leak_suspects_cheap(ttl, time.time())
        # owner-lost probe: one concurrent short ping per distinct owner
        owners: Dict[str, List[ObjectID]] = {}
        for oid, e in list(self.store._entries.items()):
            if e.sealed and not e.freed and e.owner:
                owners.setdefault(e.owner, []).append(oid)

        async def _probe(addr):
            try:
                await asyncio.wait_for(
                    self.worker_clients.get(addr).call("ping"), 2.0)
                return addr, True
            except asyncio.TimeoutError:
                return addr, True  # alive-but-busy is not owner loss
            except Exception:
                return addr, False

        for addr, alive in await asyncio.gather(
                *(_probe(a) for a in owners)):
            if alive:
                continue
            for oid in owners[addr]:
                e = self.store._entries.get(oid)
                if e is None:
                    continue
                leaks.append({"kind": "owner_lost", "object_id": oid.hex(),
                              "owner": addr, "size": e.size,
                              "pins": e.pinned})
        for rec in leaks:
            rec["node"] = self.node_id.hex()[:12]
        return leaks

    # -------------------------------------------------------- object transfer

    async def handle_read_chunk(self, object_id: ObjectID, offset: int,
                                length: int, with_crc: bool = False):
        """Serve a chunk of a local object to a remote agent (reference:
        chunked object push/pull, object_manager.proto:61).  Serves sealed
        entries, same-host proxies, and the SEALED RANGES of an in-progress
        pull (partial-object serving — the chunk ledger publishes each
        landed chunk, so this node relays a broadcast after one chunk-time;
        an uncovered range raises a typed ChunkNotAvailable the puller
        re-stripes).

        SENDER-SIDE ZERO-COPY: the reply carries a memoryview straight
        over the shm mapping — no intermediate ``bytes`` slice on this
        side (the hot-path lint pins that).  This is safe on
        interpreters whose transport write() CONSUMES the buffer before
        returning (<= 3.11: the selector transport sends what it can and
        copies the remainder into its own bytearray): the dispatch
        writes the reply synchronously after the handler returns,
        vectored frames flush immediately, and eviction/free run on this
        same loop, so no arena recycle can interleave.  On 3.12+ the
        transport RETAINS caller buffers across loop ticks
        (zero-copy write queue), so the view is defensively materialized
        by ``_owned_reply_buffer`` — a dangling view over a recycled
        arena range would otherwise ship another object's bytes.  No
        ``await`` may be added between the view read and the handler's
        return.

        ``with_crc`` adds a per-chunk checksum (native CRC-32C / zlib) the
        puller verifies before marking the chunk landed."""
        import pickle as _pickle
        if self.store.external_only(object_id):
            # a stale location routed a puller here after we evicted to the
            # external tier: restore off-loop first, never inline on the
            # serving loop
            await self._restore_external(object_id)
        view = _owned_reply_buffer(
            self.store.read_chunk_view(object_id, offset, length))
        m = transfer_metrics()
        if m is not None:
            m["bytes"].inc_key(KEY_CHUNK_OUT, view.nbytes)
        if with_crc:
            crc, algo = chunk_checksum(view)
            return {"crc": crc, "algo": algo,
                    "data": _pickle.PickleBuffer(view)}
        return _pickle.PickleBuffer(view)

    # -- bulk transfer channel (core/bulk_transfer.py) --------------------

    async def handle_bulk_info(self):
        """The bulk transfer channel's address on this node (None when the
        channel failed to start — peers keep the RPC chunk path)."""
        if self._bulk_server is None:
            return {"address": None}
        return {"address": f"{self.server.host}:{self._bulk_server.port}"}

    async def _bulk_acquire(self, object_id: ObjectID, offset: int,
                            length: int):
        """Runs on the agent loop for a bulk serving THREAD: resolve a
        pinned view like handle_read_chunk, but pin-protected — the
        thread pushes the view into the kernel outside this loop, so the
        same-tick no-recycle argument does not apply; the pin makes
        eviction skip the record and defers frees instead.

        -> (view, kind, full): sealed entries/proxies grant the WHOLE
        object (full=True) so the serving connection caches ONE pinned
        grant per object instead of marshalling onto this loop per chunk;
        partial holders grant per-chunk (their covered ranges change
        every chunk-time)."""
        if self.store.external_only(object_id):
            await self._restore_external(object_id)
        e = self.store._entries.get(object_id)
        full = (e is not None and e.sealed and not e.freed) or (
            e is None and object_id in self.store._proxies)
        if full:
            size = (e.size if e is not None
                    else self.store._proxies[object_id].size)
            view = self.store.read_chunk_view(object_id, 0, size)
        else:
            view = self.store.read_chunk_view(object_id, offset, length)
        kind = self.store.pin_for_serve(object_id)
        return view, kind, full

    async def _bulk_release(self, object_id: ObjectID,
                            kind: Optional[str]):
        if kind is not None:
            await self._unpin_and_chain(object_id, kind)

    def _transfer_executor(self):
        if self._transfer_pool is None:
            import concurrent.futures
            self._transfer_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=max(4,
                                get_config().object_transfer_parallelism),
                thread_name_prefix="bulk-land")
        return self._transfer_pool

    def _get_bulk_pool(self):
        if self._bulk_pool is None:
            from .bulk_transfer import BulkPool
            self._bulk_pool = BulkPool()
        return self._bulk_pool

    def _bulk_addr_for(self, addr: str) -> Optional[str]:
        """The peer's bulk-channel address, cached per agent.  Unknown
        peers kick ONE background resolution (``bulk_info`` RPC) and the
        caller uses the asyncio chunk path meanwhile — the next chunk
        rides the bulk channel."""
        cached = self._bulk_addrs.get(addr, "unresolved")
        if isinstance(cached, str) and cached != "unresolved":
            return cached
        if cached != "unresolved":
            return None  # in flight (None) or peer has none (False)
        self._bulk_addrs[addr] = None

        async def _resolve():
            try:
                info = await self.agent_clients.get(addr).call(
                    "bulk_info", _timeout=5.0)
                self._bulk_addrs[addr] = info.get("address") or False
            except Exception:
                self._bulk_addrs.pop(addr, None)  # retry on a later chunk

        t = asyncio.ensure_future(_resolve())
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)
        return None

    async def _bulk_fetch_chunk(self, object_id: ObjectID, addr: str,
                                bulk_addr: str, stripe: int,
                                sink: memoryview, off: int, n: int,
                                with_crc: bool, timeout_s: float) -> int:
        """Run one bulk fetch on the landing executor.  The finally block
        restores the no-late-write guarantee the asyncio path gets from
        call_into: if this coroutine is cancelled or times out while the
        executor thread is still landing into ``sink``, the socket is
        killed and the thread WAITED OUT before control returns — the
        caller may recycle the arena range behind ``sink`` right after."""
        import concurrent.futures
        pool = self._get_bulk_pool()
        cfut = self._transfer_executor().submit(
            pool.fetch, addr, bulk_addr, stripe, object_id, off, n, sink,
            with_crc, timeout_s)
        try:
            return await asyncio.wait_for(asyncio.wrap_future(cfut),
                                          timeout_s + 5.0)
        finally:
            # the guarantee must actually HOLD, not be attempted once: a
            # thread still inside create_connection registers its socket
            # only after connecting (one drop would miss it), so drop
            # again each round until the future is genuinely done — with
            # the socket dead, recv/sendall fail within one syscall,
            # bounding the loop to the connect timeout.  Only THIS
            # stripe's socket dies: the other stripes' healthy in-flight
            # fetches from the same source must not become collateral.
            while not cfut.done():
                pool.drop_stripe(bulk_addr, stripe)
                await asyncio.get_event_loop().run_in_executor(
                    None, lambda: concurrent.futures.wait([cfut], 5.0))

    async def handle_fetch_object(self, object_id: ObjectID, size: int,
                                  locations: List[Tuple[str, str]],
                                  owner: Optional[str] = None,
                                  pin: bool = False,
                                  pinner: Optional[str] = None):
        """Ensure `object_id` is in the local store, pulling from a remote node
        if needed. Returns {path, size, pinned} (reference: PullManager
        admission-controlled prioritized pulls + PushManager chunked
        transfer).

        ``pin=True`` atomically pins the located object for the caller
        before replying (no await between locate and pin, and this loop is
        the only evictor — so a ``pinned: True`` reply guarantees the path
        stays valid until the caller's ``store_unpin_read``).  Followers of
        a deduped pull pin independently: the shared in-flight future
        carries only {path, size}.

        Broadcast shape: the source location is picked at RANDOM from the
        owner's list, and a completed pull REPORTS this node back to the
        owner — so an N-node broadcast fans out over a doubling set of
        sources (tree propagation) instead of hammering the origin."""
        res = await self._locate_or_pull(object_id, size, locations, owner)
        res = dict(res)
        # A pin needs a ledger entry or it can never be drained: grant only
        # when the caller identifies itself.
        kind = self.store.pin_for_read(object_id) if (pin and pinner) else None
        res["pinned"] = kind is not None
        if kind and pinner:
            kinds = self._read_pins.setdefault(pinner, {}).setdefault(
                object_id, {})
            first = not kinds
            kinds[kind] = kinds.get(kind, 0) + 1
            if first:
                # transitions-only stamping: this consumer's FIRST pin on
                # the object (further pins on the same grant are silent);
                # the timestamp feeds the pin-TTL leak detector
                self._pin_first_ts.setdefault((pinner, object_id),
                                              time.time())
                self._obj_event(object_id, object_explain.ObjectEvent.PINNED,
                                holder=pinner)
        return res

    async def _locate_or_pull(self, object_id: ObjectID, size: int,
                              locations: List[Tuple[str, str]],
                              owner: Optional[str]):
        if self.store.external_only(object_id):
            res = await self._restore_external(object_id)
            if res is not None:
                return res
        if self.store.contains(object_id):
            located = self.store.get_path(object_id)
            # None: the only copy is an external record whose restore just
            # failed (transient tier error) — fall through to the pull
            # path, which can stripe over the URI and other holders
            if located is not None:
                path, sz = located
                return {"path": path, "size": sz}
        e = self.store._entries.get(object_id)
        if e is not None and not e.freed:
            # Created locally but not sealed yet: the writer's one-way seal
            # (or its in-progress copy) is still in flight — park on it
            # rather than treating a local object as remote.  (A freed-
            # deferred entry is sealed but DELETED: fall through to the
            # remote pull instead of serving it.)
            if await self.store.wait_sealed(object_id, 30.0):
                located = self.store.get_path(object_id)
                if located is not None:
                    path, sz = located
                    return {"path": path, "size": sz}
        # Dedup concurrent pulls of the same object: followers await the
        # leader's transfer instead of pulling a second copy.
        inflight = self._inflight_pulls.get(object_id)
        if inflight is not None:
            return dict(await asyncio.shield(inflight))
        fut = asyncio.get_event_loop().create_future()
        self._inflight_pulls[object_id] = fut
        try:
            res = await self._pull_object(object_id, size, locations, owner)
            if not fut.done():
                fut.set_result(res)
            return res
        except BaseException as e:
            if not fut.done():
                fut.set_exception(e)
            fut.exception()  # mark retrieved for followers that never await
            raise
        finally:
            self._inflight_pulls.pop(object_id, None)

    async def _restore_external(self, object_id: ObjectID) -> Optional[dict]:
        """Restore an external-tier-only object into the local store with
        the network read OFF-LOOP (a gs:// download must not freeze
        heartbeats/lease grants for its duration — the store's synchronous
        ``_maybe_restore`` stays only as the local-disk / direct-store
        path).  Deduped through its own in-flight map so concurrent
        readers share ONE external fetch; the shared future resolves to
        the result dict OR None — never an exception — so followers fall
        back to the normal locate/pull paths exactly like the leader
        (``_inflight_pulls`` futures stay dict-only; mixing the two maps
        would hand a follower None where it expects a dict)."""
        inflight = self._inflight_restores.get(object_id)
        if inflight is not None:
            return await asyncio.shield(inflight)
        loop = asyncio.get_event_loop()
        fut = loop.create_future()
        self._inflight_restores[object_id] = fut
        res: Optional[dict] = None
        try:
            uri = self.store._spilled_external.get(object_id)
            if uri is not None:
                wfut = self.store._ext_writes.get(object_id)
                if wfut is not None:
                    # reader raced the spill write: wait it out off-loop
                    await loop.run_in_executor(None,
                                               lambda: wfut.result(60.0))
                data = await loop.run_in_executor(
                    None, external_spill.timed_read, uri)
                self.store.restore_external_bytes(object_id, data)
                located = self.store.get_path(object_id)
                if located is not None:
                    res = {"path": located[0], "size": located[1]}
        except asyncio.CancelledError:
            raise
        except Exception:      # leader AND followers fall back to the
            res = None         # normal locate/pull paths
            # and the store's SYNC fallback must not re-attempt the read
            # on the event loop right after this off-loop one failed
            self.store._ext_backoff[object_id] = time.monotonic() + 5.0
        finally:
            if not fut.done():
                fut.set_result(res)
            self._inflight_restores.pop(object_id, None)
        return res

    @property
    def _inflight_restores(self) -> Dict[ObjectID, "asyncio.Future"]:
        if not hasattr(self, "_inflight_restores_map"):
            self._inflight_restores_map: Dict[ObjectID, "asyncio.Future"] = {}
        return self._inflight_restores_map

    def _trace_transfer(self, **ev):
        """Opt-in per-transfer timeline (RAYTPU_TRANSFER_TRACE_DIR): one
        JSONL per agent recording every chunk pull / zero-copy attach with
        wall-clock start/end — the artifact that shows where broadcast
        overlap lives (or dies) on a given box."""
        d = os.environ.get("RAYTPU_TRANSFER_TRACE_DIR")
        if not d:
            return
        try:
            import json as _json
            with open(os.path.join(d, f"transfer-{os.getpid()}.jsonl"),
                      "a") as f:
                f.write(_json.dumps(
                    {"node": self.node_id.hex()[:12], **ev}) + "\n")
        except Exception:
            pass

    async def _pull_object(self, object_id: ObjectID, size: int,
                           locations: List[Tuple[str, str]],
                           owner: Optional[str]):
        import random
        async with self._pull_sem:
            if self.store.contains(object_id):
                located = self.store.get_path(object_id)
                if located is not None:
                    path, sz = located
                    return {"path": path, "size": sz}
                # external-only record whose restore failed: pull instead
            cfg = get_config()
            # External-tier URIs ("external" locations, e.g. a gs://
            # object the spilling node registered before dying) are valid
            # CHUNK sources for the striped pull, but not RPC endpoints:
            # keep them out of the zero-copy probe loop.
            ext_sources = [addr for _nid, addr in locations
                           if is_external_address(addr)]
            candidates = [(nid, addr) for nid, addr in locations
                          if addr != self.server.address
                          and not is_external_address(addr)]
            random.shuffle(candidates)
            # Same-host fast path: attach the source's pool slice instead of
            # copying bytes through a socket — the source pins the object for
            # us until we free our proxy (zero-copy same-host broadcast).
            # RAYTPU_DISABLE_ZERO_COPY=1 forces the chunked byte path — the
            # bench/test seam for exercising what distinct hosts do.
            if os.environ.get("RAYTPU_DISABLE_ZERO_COPY") == "1":
                candidates_zc = []
            else:
                candidates_zc = candidates
            for node_id, addr in candidates_zc:
                client = self.agent_clients.get(addr)
                try:
                    info = await client.call("object_info",
                                             object_id=object_id)
                except Exception:
                    continue
                if (not info or info.get("proxy") or info.get("partial")
                        or info.get("host_key") != self.host_key):
                    # partial holders can't grant a pin (unsealed entry):
                    # byte pulls may stripe onto them, attaches may not
                    continue
                try:
                    t_pin = time.time()
                    if await client.call("pin_object", object_id=object_id):
                        self.store.add_proxy(object_id, info["path"],
                                             info["size"], addr)
                        m = transfer_metrics()
                        if m is not None:
                            m["bytes"].inc_key(KEY_PROXY_IN, info["size"])
                        object_explain.ledger_record(
                            object_explain.KEY_TRANSFER_PROXY, info["size"])
                        self._obj_event(
                            object_id,
                            object_explain.ObjectEvent.TRANSFERRED,
                            source=addr, size=info["size"], zero_copy=True)
                        self._record_transfer(
                            object_id, info["size"], "proxy", t_pin, "ok",
                            source=addr)
                        self._trace_transfer(
                            kind="proxy_attach", object=object_id.hex()[:12],
                            source=addr, bytes=info["size"],
                            t0=t_pin, t1=time.time())
                        if owner:
                            # A proxy holder IS a source for byte pullers
                            # (read_chunk attaches the proxied slice);
                            # same-host pullers skip it via
                            # object_info.proxy and go to the origin (no
                            # proxy-of-proxy pin chains).
                            self._register_object_location(owner, object_id)
                        return {"path": info["path"], "size": info["size"]}
                except Exception:
                    continue
            return await self._pull_object_chunks(
                object_id, size,
                [addr for _nid, addr in candidates] + ext_sources,
                owner, cfg)

    def _register_object_location(self, owner: str, object_id: ObjectID):
        """Tell the owner this node now holds (part of) the object.

        Retried with an idempotency token (``call_retry``): the old
        fire-and-forget notify meant one dropped frame permanently hid this
        source from the owner's location view.  Runs as a background task —
        the pull's caller shouldn't wait out a retry backoff — with a
        strong ref so the loop can't GC it mid-flight."""
        self._location_update(owner, "add_object_location", object_id)

    def _deregister_object_location(self, owner: str, object_id: ObjectID):
        """Withdraw an early (partial) registration after a FAILED pull:
        the owner's location list must not keep routing pullers at a node
        that freed the segment."""
        self._location_update(owner, "remove_object_location", object_id)

    def _location_update(self, owner: str, method: str,
                         object_id: ObjectID,
                         node_id: Optional[str] = None,
                         address: Optional[str] = None):
        """Background location add/remove, SEQUENCED per (owner, object):
        updates for one object chain behind each other, so a failed pull's
        remove can never overtake its own still-retrying add (unordered
        tasks could re-register a freed segment forever).

        ``node_id``/``address`` default to THIS node; the external-spill
        hook passes ``(EXTERNAL_NODE_ID, uri)`` to register a copy that is
        not on any node."""
        key = (owner, object_id)
        prev = self._loc_updates.get(key)
        loc_node = node_id if node_id is not None else self.node_id.hex()
        loc_addr = address if address is not None else self.server.address

        async def _send():
            if prev is not None:
                try:
                    await asyncio.shield(prev)
                except Exception:
                    pass
            try:
                await self.worker_clients.get(owner).call_retry(
                    method, object_id=object_id,
                    node_id=loc_node,
                    address=loc_addr, _timeout=15.0)
            except Exception:
                pass

        t = asyncio.ensure_future(_send())
        self._loc_updates[key] = t
        self._bg_tasks.add(t)

        def _done(task, _key=key):
            self._bg_tasks.discard(task)
            if self._loc_updates.get(_key) is task:
                del self._loc_updates[_key]

        t.add_done_callback(_done)

    async def _pull_object_chunks(self, object_id: ObjectID, size: int,
                                  sources: List[str], owner: Optional[str],
                                  cfg) -> dict:
        """Chunk-ledger striped byte pull (the cross-host broadcast path).

        Chunks are scheduled across ALL known sources concurrently
        (per-source windows, work-stealing of slow chunks, chunk-granular
        retry on another source), every landed chunk is published so this
        node relays the broadcast while still pulling, and the owner's
        location view is re-polled mid-pull to fold in new sources.  See
        ``core/transfer.py`` for the engine."""
        if not sources and not owner:
            raise RuntimeError(
                f"failed to fetch {object_id}: no locations and no owner")
        import random as _random
        self.store.create(object_id, size)
        # Transfer pin for the pull's whole duration: partial serving
        # registers this node with the owner after the FIRST chunk, so an
        # owner-side free can now arrive MID-PULL — unpinned, it would
        # complete immediately and recycle the arena range under the
        # in-flight chunk landings (create+pin run in one loop tick, so
        # the free cannot slip between them).  Pinned, the free defers;
        # the unpin below completes it and the pull reports "vanished".
        self.store.pin(object_id)
        seg = self.store._entries[object_id].segment
        # per-puller permuted claim order (rarest-first in spirit): the
        # pullers of one broadcast land COMPLEMENTARY ranges, so partial
        # serving actually relays — in lockstep 0..N order every peer only
        # ever holds the prefix the others already have
        n_chunks = max(1, -(-size // cfg.object_transfer_chunk_bytes))
        order = list(range(n_chunks))
        _random.shuffle(order)
        ledger = ChunkLedger(size, cfg.object_transfer_chunk_bytes,
                             order=order)
        partial = cfg.object_transfer_partial_serving
        registered = False
        # wire-rate knobs: parallel sockets per source (sticky per chunk)
        # and adaptive per-request growth in base-chunk runs
        sock_n = max(1, cfg.transfer_sockets_per_source)
        run_max = max(1, cfg.object_transfer_chunk_max
                      // max(1, cfg.object_transfer_chunk_bytes))
        sock_rr: Dict[str, int] = {}
        chunk_subs: Dict[int, int] = {}

        def clamp_run_chunks() -> int:
            # receiver-side re-clamp: a grown request must never exceed
            # the largest free arena block of THIS (receiving) store —
            # any transfer-plane landing that needs a contiguous arena
            # range (checksum scratch, restore) must fit without forcing
            # an eviction/spill mid-pull
            pool = self.store.pool
            if pool is None:
                return run_max
            try:
                lf = pool.largest_free
            except Exception:
                return 1
            return max(1, lf // max(1, cfg.object_transfer_chunk_bytes))

        def on_chunk(i, off, n, addr, t0, t1, stolen):
            nonlocal registered
            if partial:
                # publish the landed range BEFORE registering as a source:
                # a puller that finds us must find bytes
                self.store.mark_available(object_id, off, n)
            self._trace_transfer(
                kind="chunk", object=object_id.hex()[:12], source=addr,
                offset=off, bytes=n, t0=t0, t1=t1, stolen=stolen,
                socket=chunk_subs.pop(off, 0))
            if partial and not registered and owner:
                registered = True
                self._register_object_location(owner, object_id)

        async def fetch_chunk(addr, off, n):
            # sock_n == 1 keeps the historical single shared connection
            # (stripe 0); > 1 spreads chunks sticky over DEDICATED bulk
            # stripes 1..sock_n (big socket buffers, large reads) so
            # multi-MB replies stream concurrently instead of serializing
            # head-of-line with each other and the control traffic
            sub = 0
            if sock_n > 1 and not is_external_address(addr):
                sub = 1 + (sock_rr.get(addr, -1) + 1) % sock_n
                sock_rr[addr] = sock_rr.get(addr, -1) + 1
            chunk_subs[off] = sub
            return await self._fetch_chunk(object_id, seg, addr, off, n,
                                           cfg, sub)

        async def probe_source(addr):
            if is_external_address(addr):
                # external copies are complete by construction (the spill
                # write is atomic: tmp-file rename / single upload)
                ok = await asyncio.get_event_loop().run_in_executor(
                    None, external_spill.exists, addr)
                return {"full": True} if ok else None
            try:
                info = await self.agent_clients.get(addr).call(
                    "object_info", object_id=object_id, _timeout=5.0)
            except Exception:
                return None
            if not info:
                return None
            if info.get("partial"):
                return {"full": False, "ranges": info.get("ranges") or []}
            return {"full": True}

        async def refresh_sources():
            rec = await self.worker_clients.get(owner).call(
                "locate_object", object_id=object_id, timeout=0,
                _timeout=5.0)
            if rec and rec[0] == "plasma":
                return [addr for _nid, addr in rec[2]
                        if addr != self.server.address]
            return []

        puller = StripedPull(
            ledger, fetch_chunk=fetch_chunk, probe_source=probe_source,
            refresh_sources=refresh_sources if owner else None,
            on_chunk=on_chunk,
            per_source_window=cfg.object_transfer_per_source_window,
            total_window=cfg.object_transfer_parallelism,
            steal_after_s=cfg.object_transfer_steal_after_s,
            max_source_failures=cfg.object_transfer_max_source_failures,
            refresh_period_s=cfg.object_transfer_source_refresh_s,
            stall_timeout_s=cfg.object_transfer_stall_timeout_s,
            run_max_chunks=run_max,
            clamp_run_chunks=clamp_run_chunks if run_max > 1 else None)
        t_pull = time.time()
        try:
            try:
                stats = await puller.run(sources)
            except asyncio.CancelledError:
                # engine teardown already awaited every in-flight landing,
                # so freeing the segment cannot race a late chunk write
                if registered and owner:
                    self._deregister_object_location(owner, object_id)
                self._record_transfer(object_id, size, "chunked", t_pull,
                                      "cancelled")
                self.store.free(object_id)  # defers under our pin
                raise
            except BaseException as e:  # noqa: BLE001
                if registered and owner:
                    # withdraw the early partial registration — the owner
                    # must not keep routing pullers at a freed segment
                    self._deregister_object_location(owner, object_id)
                self._record_transfer(object_id, size, "chunked", t_pull,
                                      "failed")
                self.store.free(object_id)  # defers under our pin
                raise RuntimeError(
                    f"failed to fetch {object_id} from {sources}: {e}"
                ) from e
            self.store.seal(object_id)
        finally:
            # releases the transfer pin; completes any free deferred
            # during the pull (our own failure free above, or an
            # owner-side free that raced the broadcast)
            self.store.unpin(object_id)
        object_explain.ledger_record(object_explain.KEY_TRANSFER_LAND, size)
        self._obj_event(object_id, object_explain.ObjectEvent.TRANSFERRED,
                        size=size, sources=stats.get("sources_used"),
                        chunks=stats.get("chunks_done"))
        self._record_transfer(object_id, size, "chunked", t_pull, "ok",
                              stats=stats)
        self._trace_transfer(
            kind="pull_summary", object=object_id.hex()[:12], bytes=size,
            t0=t_pull, t1=time.time(), sockets_per_source=sock_n,
            chunk_max_bytes=run_max * cfg.object_transfer_chunk_bytes,
            **stats)
        if owner:
            self._register_object_location(owner, object_id)
        located = self.store.get_path(object_id)
        if located is None:
            # owner freed it mid-pull (the deferred free completed on our
            # unpin): the object is gone — report it, never serve it
            raise RuntimeError(f"object {object_id} vanished during pull")
        path, sz = located
        return {"path": path, "size": sz}

    async def _fetch_chunk(self, object_id: ObjectID, seg, addr: str,
                           off: int, n: int, cfg, sub: int = 0) -> int:
        """Land one chunk (or a grown run of base chunks) from ``addr``
        into the destination segment.

        The reply's out-of-band buffer lands DIRECTLY into the segment
        view (``call_into`` readinto-style receive) — no intermediate
        ``bytes``, no slice-assign: zero extra copies on this side beyond
        the socket read itself.  ``sub`` picks the parallel transfer
        socket to ``addr`` (sticky per chunk; see
        ``transfer_sockets_per_source``).  Returns the byte count landed;
        the engine rejects short chunks (a truncated reply must never
        seal a corrupt object)."""
        sink = seg.view()[off:off + n]
        if is_external_address(addr):
            # external-tier chunk source: range-read the URI off-loop and
            # land it like any other chunk — the ledger's short-chunk /
            # retry / source-death handling applies unchanged
            data = await asyncio.get_event_loop().run_in_executor(
                None, external_spill.read_range, addr, off, n)
            landed = len(data)
            if landed <= n:
                sink[:landed] = data
            return landed
        # a grown run carries proportionally more bytes than the base
        # chunk the timeout was tuned for: scale it, bounded
        timeout_s = min(
            cfg.object_transfer_chunk_timeout_s
            * max(1, -(-n // max(1, cfg.object_transfer_chunk_bytes))),
            max(cfg.object_transfer_chunk_timeout_s,
                cfg.object_transfer_stall_timeout_s * 2))
        with_crc = cfg.object_transfer_checksum
        if sub > 0:
            # multi-socket mode: ride the threaded bulk channel when the
            # peer advertises one (sendall/recv_into straight between shm
            # mappings and the kernel, GIL released — the asyncio RPC
            # path below stays as the fallback and the sockets=1 arm)
            bulk_addr = self._bulk_addr_for(addr)
            if bulk_addr:
                try:
                    return await self._bulk_fetch_chunk(
                        object_id, addr, bulk_addr, sub - 1, sink, off, n,
                        with_crc, timeout_s)
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    # the peer may have restarted with a NEW bulk port at
                    # the same RPC address: drop the cached bulk address
                    # so the next chunk re-resolves (riding the RPC path
                    # meanwhile) instead of permanently hammering a dead
                    # port until the source is declared dead
                    self._bulk_addrs.pop(addr, None)
                    raise
        client = self.agent_clients.get_striped(addr, sub)
        if with_crc:
            # Checksum mode trades the zero-copy landing for soundness: a
            # work-steal hedge means a straggler duplicate reply can arrive
            # AFTER another source already landed this chunk — landing
            # unverified bytes in place would overwrite a DONE chunk the
            # ledger will never re-pull (fail on DONE is a no-op).  Fetch
            # to a scratch buffer, verify, THEN copy.
            try:
                res = await client.call(
                    "read_chunk",
                    _timeout=timeout_s,
                    object_id=object_id, offset=off, length=n,
                    with_crc=True)
            except RemoteError as e:
                if isinstance(e.cause, ChunkNotAvailable):
                    raise e.cause from None
                raise
            crc, algo, data = res["crc"], res["algo"], res["data"]
            landed = data.nbytes if isinstance(data, memoryview) \
                else len(data)
            if landed == n:
                got, got_algo = chunk_checksum(data)
                if got_algo == algo and got != crc:
                    raise ChunkCrcError(
                        f"chunk [{off}, {off + n}) from {addr}: checksum "
                        f"mismatch ({got:#x} != {crc:#x})")
                sink[:n] = data
            return landed
        try:
            res = await client.call_into(
                "read_chunk", sink,
                _timeout=timeout_s,
                object_id=object_id, offset=off, length=n)
        except RemoteError as e:
            if isinstance(e.cause, ChunkNotAvailable):
                # typed partial miss: the engine re-stripes the chunk and
                # re-probes this source's advertised ranges
                raise e.cause from None
            raise
        if isinstance(res, memoryview):
            return res.nbytes     # landed in place by the sink receive
        landed = len(res)         # small in-band reply: place it ourselves
        if landed <= n:
            sink[:landed] = res
        return landed

    # ------------------------------------------------------------ OOM defense

    async def _memory_monitor_loop(self):
        """Kill a worker before the kernel OOM-killer takes the whole node.

        Reference: ``src/ray/common/memory_monitor.h:52`` + the raylet's
        worker-killing policies (``worker_killing_policy.h:64`` retriable-
        LIFO, ``worker_killing_policy_group_by_owner.h:85`` group-by-owner,
        selected by config.oom_worker_killing_policy): when node memory
        passes the threshold, kill a leased task-running worker — its task
        retries (bounded by task_oom_retries), and admission backpressure
        (fewer workers) relieves the pressure.  Actors are spared unless
        they are the only candidates (restarting an actor is costlier than
        retrying a task)."""
        cfg = get_config()
        if not cfg.memory_monitor_enabled:
            return
        try:
            import psutil
        except ImportError:
            return
        while not self._shutting_down:
            await asyncio.sleep(cfg.memory_monitor_interval_s)
            try:
                usage = psutil.virtual_memory().percent / 100.0
                if usage < cfg.memory_usage_threshold:
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                victim.state = "DRAINING"
                self._oom_kill_count += 1
                try:
                    # This loop runs ON the agent's IO loop: write the
                    # event through our async GCS client (the blocking
                    # events.record() would raise in run_async here).
                    # Keep a strong ref to the task — the loop holds only
                    # weak ones — and record_via swallows KV failures.
                    from ray_tpu.util import events
                    task = asyncio.ensure_future(events.record_via(
                        self.gcs.call, "WARNING", "memory-monitor",
                        f"killed worker {victim.worker_id[:12]}",
                        policy=cfg.oom_worker_killing_policy,
                        usage=f"{usage:.0%}",
                        owner=victim.owner or "",
                        node=self.node_id.hex()[:12]))
                    self._bg_tasks.add(task)
                    task.add_done_callback(self._bg_tasks.discard)
                except Exception:
                    pass  # the kill must proceed even with no live GCS
                cause = (
                    f"worker killed by the memory monitor: node memory "
                    f"{usage:.0%} >= threshold "
                    f"{cfg.memory_usage_threshold:.0%} "
                    f"({cfg.oom_worker_killing_policy} worker killing "
                    f"policy)")
                if victim.is_actor and victim.actor_id:
                    # _kill_worker_proc releases leases but does not tell
                    # the GCS — an unreported actor death would leave the
                    # actor ALIVE forever and hang its callers.  Actors have
                    # no lease return to consume _oom_kills, so thread the
                    # typed cause straight into the death reason instead.
                    try:
                        await self.gcs.call_retry(
                            "report_actor_death", actor_id=victim.actor_id,
                            reason=f"OutOfMemoryError: {cause}")
                    except Exception:
                        pass
                else:
                    self._oom_kills[victim.worker_id] = cause
                    # Bound the dict: an owner that dies before returning
                    # the lease never consumes its entry (insertion order =
                    # kill order, so the evictee is the oldest).
                    while len(self._oom_kills) > 256:
                        self._oom_kills.pop(next(iter(self._oom_kills)))
                await self._kill_worker_proc(victim)
                if victim.owner and not victim.is_actor:
                    # Proactive typed-death delivery: don't rely on the
                    # owner's in-flight RPC seeing EOF — tell the lease
                    # owner directly so it force-fails the connection and
                    # surfaces OutOfMemoryError promptly (the EOF path
                    # remains as backstop).
                    try:
                        await self.worker_clients.get(victim.owner).notify(
                            "worker_killed", worker_id=victim.worker_id,
                            address=victim.address, cause=cause)
                    except Exception:
                        pass
                try:
                    print(f"[memory-monitor] node memory {usage:.0%} >= "
                          f"{cfg.memory_usage_threshold:.0%}: killed worker "
                          f"{victim.worker_id[:12]} "
                          f"({cfg.oom_worker_killing_policy})",
                          flush=True)
                except Exception:
                    pass
            except asyncio.CancelledError:
                raise
            except Exception:
                pass

    def _pick_oom_victim(self):
        # Only REGISTERED leased workers are candidates: a worker that has
        # not called back yet is still booting — its task body is not
        # running, so killing it frees no task memory, and the owner's
        # lease-grant RPC is still parked in _grant_lease's registered.wait
        # (the typed death cause could only reach the owner after the full
        # register timeout, long past any reasonable ray.get deadline).
        leased = [w for w in self.workers.values()
                  if w.state == "LEASED" and w.registered.is_set()]
        tasks = [w for w in leased if not w.is_actor]
        pool = tasks or leased
        if not pool:
            return None
        if get_config().oom_worker_killing_policy == "group_by_owner":
            # Group leased workers by submitting owner; the owner with the
            # LARGEST fan-out loses its newest lease (reference:
            # worker_killing_policy_group_by_owner.h:85).  Singleton groups
            # tie-break to the newest lease overall == retriable-LIFO.
            groups: Dict[str, list] = {}
            for w in pool:
                groups.setdefault(w.owner or w.worker_id, []).append(w)
            grp = max(groups.values(),
                      key=lambda g: (len(g), max(w.leased_at for w in g)))
            return max(grp, key=lambda w: w.leased_at)
        # retriable-LIFO: the newest lease loses the least progress
        return max(pool, key=lambda w: w.leased_at)

    # ---------------------------------------------------------- observability

    async def handle_report_metrics(self, reporter: str, metrics: dict):
        """Workers/drivers push their metric-registry snapshots here
        (reference: stats export to the per-node agent, metric_exporter.h)."""
        if not hasattr(self, "_metrics"):
            self._metrics = {}
        self._metrics[reporter] = metrics
        return True

    async def _start_metrics_endpoint(self):
        """Prometheus text endpoint (reference: metrics_agent.py:375) —
        aiohttp on a random port, advertised via the node's labels."""
        try:
            from aiohttp import web
        except ImportError:
            return

        async def metrics_handler(_request):
            from ray_tpu.util.metrics import (render_prometheus,
                                              snapshot_registry)
            # Refresh the node gauges at scrape time (the telemetry loop
            # keeps them warm between scrapes), then serve the agent's own
            # registry (node gauges, RPC metrics) merged with every
            # worker/driver snapshot pushed via report_metrics.
            self._sample_telemetry()
            per = dict(getattr(self, "_metrics", {}))
            per[f"agent-{self.node_id.hex()[:12]}"] = snapshot_registry()
            return web.Response(text=render_prometheus(per),
                                content_type="text/plain")

        app = web.Application()
        app.router.add_get("/metrics", metrics_handler)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        # bind where the agent's RPC server binds so the dashboard head can
        # scrape remote nodes at their advertised address
        site = web.TCPSite(runner, self.server.host, 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self._metrics_runner = runner
        self.labels["metrics_port"] = str(port)

    def _sample_telemetry(self):
        """One sample of this node's runtime state into the telemetry
        gauges: shm-pool occupancy (used/free/largest-free, the PR-1
        introspection), outstanding read pins, scheduler queue depth, live
        worker count, and resource capacity.  Called by the periodic
        telemetry loop and again at /metrics scrape time for freshness."""
        g = _telemetry_gauges()
        if g is None:
            return
        tags = {"node": self.node_id.hex()[:12]}
        g["workers"].set(len(self.workers), tags)
        g["workers_leased"].set(
            sum(1 for w in self.workers.values() if w.state == "LEASED"),
            tags)
        g["lease_queue"].set(len(self.lease_queue), tags)
        if object_explain.enabled():
            # every raytpu_object_* / raytpu_mem_* series hangs off the ONE
            # object-plane kill switch (A/B discipline: off means zero
            # series, not zero-valued series)
            st = self.store.stats()
            used = st.get("used", 0)
            cap = st.get("capacity", 0)
            g["store_used"].set(used, tags)
            g["store_capacity"].set(cap, tags)
            g["store_free"].set(max(0, cap - used), tags)
            g["store_largest_free"].set(st.get("largest_free_block", 0),
                                        tags)
            g["store_objects"].set(st.get("num_objects", 0), tags)
            g["store_pinned"].set(st.get("num_pinned", 0), tags)
            g["mem_frag"].set(st.get("frag_fraction", 0.0), tags)
            hist = st.get("free_block_hist") or {}
            g["mem_free_blocks"].set(hist.get("num_free_blocks", 0), tags)
            for tier, bkey, okey in (
                    ("local", "spilled_local_bytes", "num_spilled_local"),
                    ("external", "spilled_external_bytes",
                     "num_spilled_external")):
                ttags = {"node": tags["node"], "tier": tier}
                g["mem_spill_bytes"].set(st.get(bkey, 0), ttags)
                g["mem_spill_objects"].set(st.get(okey, 0), ttags)
            g["mem_leaks"].set(
                len(self._leak_suspects_cheap(
                    get_config().object_pin_leak_ttl_s, time.time())),
                tags)
        g["read_pins"].set(
            sum(count for per in self._read_pins.values()
                for kinds in per.values() for count in kinds.values()),
            tags)
        g["oom_kills"].set(self._oom_kill_count, tags)
        try:
            # session-dir filesystem fullness (statvfs is a syscall, not
            # a walk): logs + local spill land here, so this is the disk
            # that takes the cluster down when it fills
            st = os.statvfs(self.session_dir)
            total = st.f_blocks * st.f_frsize
            free = st.f_bavail * st.f_frsize
            if total > 0:
                g["disk_used_frac"].set(1.0 - free / total, tags)
                g["disk_free"].set(free, tags)
        except (OSError, ValueError):
            pass
        avail = self.available.to_dict()
        for k, total in self.total.to_dict().items():
            rtags = {"node": tags["node"], "resource": k}
            g["resource_available"].set(avail.get(k, 0.0), rtags)
            g["resource_total"].set(total, rtags)

    async def _telemetry_loop(self, period_s: float = 2.0):
        """Periodic node self-measurement (reference: the per-node stats
        reporters feeding metrics_agent.py) — keeps the gauges live even
        when nothing scrapes, so a snapshot pulled through report_metrics
        or a debugger is never minutes stale."""
        while not self._shutting_down:
            try:
                self._sample_telemetry()
            except Exception:
                pass
            await asyncio.sleep(period_s)

    async def _log_monitor_loop(self):
        """Tail worker log files and publish new lines to the GCS pubsub
        topic ``worker_logs`` (reference: _private/log_monitor.py:103 —
        worker stdout/stderr shows up at the driver)."""
        logdir = os.path.join(self.session_dir, "logs")
        offsets: Dict[str, int] = {}
        while not self._shutting_down:
            await asyncio.sleep(0.5)
            try:
                batch = []
                for fn in os.listdir(logdir):
                    if not fn.startswith("worker-"):
                        continue
                    path = os.path.join(logdir, fn)
                    off = offsets.get(fn, 0)
                    size = os.path.getsize(path)
                    if size <= off:
                        continue
                    with open(path, "rb") as f:
                        f.seek(off)
                        data = f.read(min(size - off, 1 << 20))
                    offsets[fn] = off + len(data)
                    lines = data.decode(errors="replace").splitlines()
                    if lines:
                        batch.append({"worker": fn[len("worker-"):-4],
                                      "lines": lines})
                if batch and self.gcs:
                    await self.gcs.call(
                        "publish", topic="worker_logs",
                        payload={"node": self.node_id.hex()[:12],
                                 "batch": batch})
            except asyncio.CancelledError:
                raise
            except Exception:
                pass

    # ----------------------------------------------------------------- misc

    async def handle_ping(self):
        return "pong"

    async def handle_list_logs(self) -> List[dict]:
        """Session log files on this node (reference: dashboard log module's
        per-node listing)."""
        logdir = os.path.join(self.session_dir, "logs")
        out = []
        try:
            for name in sorted(os.listdir(logdir)):
                p = os.path.join(logdir, name)
                if os.path.isfile(p):
                    out.append({"name": name, "size": os.path.getsize(p)})
        except OSError:
            pass
        return out

    async def handle_tail_log(self, name: str, nbytes: int = 65536) -> str:
        """Last `nbytes` of one session log file.  The name is confined to
        the log directory (no path components)."""
        if "/" in name or "\\" in name or name.startswith("."):
            return "(invalid log name)"
        p = os.path.join(self.session_dir, "logs", name)
        try:
            size = os.path.getsize(p)
            with open(p, "rb") as f:
                if size > nbytes:
                    f.seek(size - nbytes)
                return f.read(nbytes).decode("utf-8", "replace")
        except OSError as e:
            return f"(unreadable: {e})"

    async def handle_node_info(self):
        return {"node_id": self.node_id.hex(), "address": self.server.address,
                "total": self.total.to_dict(), "available": self.available.to_dict(),
                "num_workers": len(self.workers),
                "workers": {wid: {"state": w.state, "pid": w.pid,
                                  "actor_id": w.actor_id}
                            for wid, w in self.workers.items()},
                "store": self.store.stats(),
                "oom_kills": self._oom_kill_count,
                "queue_len": len(self.lease_queue),
                "draining": self._draining,
                "backpressure_rejects": dict(self._bp_rejects),
                "loop_busy_fraction": getattr(
                    getattr(self, "_loop_monitor", None),
                    "busy_fraction", None),
                "queued_demands": [r.resources for r in self.lease_queue],
                "cluster_view": {nid: {"available": v.available, "alive": v.alive}
                                 for nid, v in self.cluster_view.items()}}
