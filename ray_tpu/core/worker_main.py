"""Worker process entrypoint (reference: ``python/ray/_private/workers/default_worker.py``).

Spawned by the node agent with connection info in the environment.  Starts the
CoreWorker RPC server on the IO thread, registers with the agent, then parks the
main thread in the executor loop so user tasks run on the main thread.
"""

from __future__ import annotations

import os
import sys


def main():
    # SIGUSR1 → dump all thread stacks to stderr (lands in the worker log).
    # Debug hook behind `ray stack`-style tooling (reference: py-spy via the
    # dashboard reporter; here faulthandler is dependency-free).
    import faulthandler
    import signal
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    gcs_address = os.environ["RAYTPU_GCS_ADDRESS"]
    agent_address = os.environ["RAYTPU_AGENT_ADDRESS"]
    node_id = os.environ["RAYTPU_NODE_ID"]
    worker_id = os.environ["RAYTPU_WORKER_ID"]
    session_dir = os.environ.get("RAYTPU_SESSION_DIR", "/tmp/raytpu")

    from .config import Config, set_config
    cfg_json = os.environ.get("RAYTPU_CONFIG_JSON")
    if cfg_json:
        set_config(Config.from_json(cfg_json))

    from .core_worker import CoreWorker
    from .ids import WorkerID
    from .rpc import run_async

    w = CoreWorker(mode="worker", gcs_address=gcs_address,
                   agent_address=agent_address, node_id=node_id,
                   session_dir=session_dir)
    w.worker_id = WorkerID.from_hex(worker_id)
    w.start()
    # Populate the api-module state so context-dependent utilities (pubsub,
    # util.state, runtime_context helpers) resolve the GCS address inside
    # worker processes too, not just in drivers (reference: workers share the
    # same ``ray._private.worker.global_worker`` context as drivers).
    from . import api
    api._state.worker = w
    api._state.gcs_address = gcs_address
    api._state.session_dir = session_dir
    # retried + token'd: a registration reply lost to a flaky link must
    # not leave the worker unregistered (the agent would reap it) nor
    # register it twice
    res = run_async(w.agent.call_retry("register_worker",
                                       worker_id=worker_id,
                                       address=w.address, pid=os.getpid()))
    if res.get("shutdown"):
        sys.exit(0)

    # Agent watchdog: if our node agent dies (crash, node kill), exit instead
    # of lingering as an orphan (reference: workers die with their raylet).
    import threading
    import time as _time

    def _watchdog():
        misses = 0
        while True:
            _time.sleep(2.0)
            try:
                run_async(w.agent.call("ping", _timeout=3.0), timeout=5)
                misses = 0
            except Exception:
                misses += 1
                if misses >= 3:
                    os._exit(0)

    threading.Thread(target=_watchdog, name="agent-watchdog",
                     daemon=True).start()
    try:
        w.run_executor_loop()
    except KeyboardInterrupt:
        pass
    finally:
        w.shutdown()


if __name__ == "__main__":
    main()
