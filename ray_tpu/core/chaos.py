"""Seeded, deterministic fault-injection plane (the chaos harness).

Reference: the chaos release tests (``chaos_network_delay.yaml`` and the
``NodeKillerActor`` in ``test_utils.py:1401``) that kill nodes and degrade
links under real workloads.  Here the harness lives INSIDE the runtime:
every process installs one :class:`FaultInjector` from config/env
(``RAYTPU_CHAOS_SPEC``), and the RPC layer (``core/rpc.py``) consults it on
every frame — so a single JSON spec degrades the whole cluster coherently,
and the same seed reproduces the same injected-fault sequence.

Spec format (JSON)::

    {
      "seed": 7,
      "rules": [
        {"kind": "delay",        "ms": 200, "prob": 1.0},
        {"kind": "drop_request", "prob": 0.05},
        {"kind": "drop_reply",   "prob": 0.05, "method": "kv_put"},
        {"kind": "fail_before",  "prob": 0.5,  "method": "register_actor"},
        {"kind": "fail_after",   "prob": 0.5,  "method": "kv_put"},
        {"kind": "partition",    "peer": "127.0.0.1:6379", "times": 10}
      ],
      "kills": [{"after_s": 3.0, "target": "worker", "node": "ab12"},
                {"kind": "preempt_node", "after_s": 5.0, "notice_s": 2.0,
                 "node": "cd34"}]
    }

Rule fields: ``kind`` (required), ``prob`` (default 1.0), ``ms`` (delay
only), ``method`` (exact RPC method name; absent = every method), ``peer``
(substring of the peer address — per-link faults; absent = every link),
``times`` (max injections for this rule; absent = unlimited).

Fault semantics (where each hook lives):

* ``delay`` — client-side: sleep before the frame is written.
* ``drop_request`` — client-side: the frame is not written and the
  connection is ABORTED (a lost frame on a live TCP stream is
  indistinguishable from the link dying), so every pending call fails fast
  with ``ConnectionLost`` instead of hanging to its timeout.
* ``drop_reply`` — server-side: the handler RAN (state committed) but the
  reply is lost and the connection aborted — the window that exercises the
  client's idempotent retry (``call_retry`` + server dedup).
* ``fail_before`` — server-side: the handler is NOT executed; the caller
  sees a :class:`ChaosFault` RemoteError (safe to retry blindly).
* ``fail_after`` — server-side: the handler executed and its result was
  recorded in the idempotency cache, but the caller sees a ChaosFault —
  a retry with the same token must observe the committed result.
* ``partition`` — client-side: calls to matching peers raise
  ``ConnectionLost`` immediately (link blackhole).
* ``kills`` — the node agent runs the schedule: at ``after_s`` seconds
  after install it kills one worker process (deterministic victim: first
  registered non-actor worker by worker id; ``node`` restricts the
  schedule entry to agents whose node id starts with that prefix).
* ``kills`` entries with ``kind: "preempt_node"`` (or ``target: "node"``)
  preempt the WHOLE matching node instead: the agent receives a shutdown
  notice of ``notice_s`` seconds and drains — stops accepting leases,
  re-homes sole-copy objects to the external spill tier / a peer, lets
  outstanding leases return, deregisters — with a hard kill when the
  notice expires.  ``notice_s: 0`` is the no-warning preemption (the node
  just dies; recovery rides the external tier and lineage).

Determinism: decisions are not drawn from a shared RNG stream (call
interleaving would perturb them) — the n-th evaluation of rule *i* for
method *m* hashes ``(seed, i, m, n)`` into a uniform fraction, so the
decision sequence per (rule, method) is a pure function of the spec.

Every injected fault increments ``raytpu_chaos_injected_total{kind}`` so
chaos is observable in the existing telemetry plane, and is appended to a
bounded decision log (``decision_log()``) that tests compare across runs.

Runtime control: GCS ``chaos_set``/``chaos_clear`` (see ``core/gcs.py``)
broadcast a new spec over pubsub and heartbeat piggyback; the ``raytpu
chaos`` CLI subcommand drives them.
"""

from __future__ import annotations

import collections
import hashlib
import json
import threading
from typing import Any, Dict, List, Optional

from .config import get_config


class ChaosFault(RuntimeError):
    """A chaos-injected failure.  By definition retryable: the runtime
    raised it deliberately, either before any state changed (fail_before)
    or after recording the committed result in the idempotency cache
    (fail_after) — ``RpcClient.call_retry`` treats it like a lost
    connection."""


#: control-plane methods the injector never faults — chaos must not be able
#: to lock out the switch that turns chaos off
_EXEMPT_METHODS = frozenset(
    {"chaos_set", "chaos_clear", "chaos_get", "chaos_update"})


def _build_chaos_counter():
    from ray_tpu.util.metrics import Counter
    return Counter("raytpu_chaos_injected_total",
                   "faults injected by the chaos plane, by kind",
                   tag_keys=("kind",))


_chaos_counter_get = None


def _chaos_counter():
    global _chaos_counter_get
    if _chaos_counter_get is None:
        # deferred to first call: importing util.metrics at module import
        # time re-enters the ray_tpu package init (circular import)
        from ray_tpu.util.metrics import lazy
        _chaos_counter_get = lazy(_build_chaos_counter)
    return _chaos_counter_get()


class _Rule:
    __slots__ = ("kind", "prob", "ms", "method", "peer", "times", "hits")

    def __init__(self, raw: Dict[str, Any]):
        self.kind = str(raw["kind"])
        self.prob = float(raw.get("prob", 1.0))
        self.ms = float(raw.get("ms", 0.0))
        self.method = raw.get("method")
        self.peer = raw.get("peer")
        self.times = raw.get("times")
        self.hits = 0


class FaultInjector:
    """One per process; every RpcClient/RpcServer in the process consults
    it (plus the node agent's kill-schedule loop)."""

    def __init__(self, spec: Any):
        if isinstance(spec, str):
            spec = json.loads(spec) if spec.strip() else {}
        self.spec: Dict[str, Any] = dict(spec or {})
        self.seed = int(self.spec.get("seed", 0))
        self.rules: List[_Rule] = [_Rule(r) for r in self.spec.get("rules", [])]
        self.kills: List[dict] = list(self.spec.get("kills", []))
        self._lock = threading.Lock()
        self._counters: Dict[tuple, int] = {}
        self._counts: Dict[str, int] = {}
        self._log: "collections.deque" = collections.deque(maxlen=4096)

    # ------------------------------------------------------------- decisions

    def _fraction(self, rule_idx: int, method: str, n: int) -> float:
        """Deterministic uniform fraction for the n-th evaluation of one
        rule against one method — a pure function of (seed, rule, method,
        n), independent of cross-method call interleaving."""
        h = hashlib.sha256(
            f"{self.seed}|{rule_idx}|{method}|{n}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64

    def _roll(self, rule_idx: int, rule: _Rule, method: str) -> bool:
        with self._lock:
            if rule.times is not None and rule.hits >= rule.times:
                return False
            key = (rule_idx, method)
            n = self._counters.get(key, 0)
            self._counters[key] = n + 1
        hit = (rule.prob >= 1.0
               or self._fraction(rule_idx, method, n) < rule.prob)
        if hit:
            with self._lock:
                rule.hits += 1
                self._log.append((rule.kind, method, n))
        return hit

    @staticmethod
    def _matches(rule: _Rule, method: str, peer: Optional[str]) -> bool:
        if rule.method is not None and rule.method != method:
            return False
        if rule.peer is not None and (peer is None or rule.peer not in peer):
            return False
        return True

    # ----------------------------------------------------------------- hooks

    def delay_s(self, method: str, peer: Optional[str] = None) -> float:
        """Client-side added latency for one frame (sum of matching delay
        rules that fire)."""
        if method in _EXEMPT_METHODS:
            return 0.0
        total = 0.0
        for i, r in enumerate(self.rules):
            if (r.kind == "delay" and self._matches(r, method, peer)
                    and self._roll(i, r, method)):
                total += r.ms / 1000.0
        if total > 0.0:
            self.record("delay")
        return total

    def should(self, kind: str, method: str,
               peer: Optional[str] = None) -> bool:
        """True iff a rule of ``kind`` fires for this (method, peer) call;
        records the injection when it does."""
        if method in _EXEMPT_METHODS:
            return False
        for i, r in enumerate(self.rules):
            if (r.kind == kind and self._matches(r, method, peer)
                    and self._roll(i, r, method)):
                self.record(kind)
                return True
        return False

    # ------------------------------------------------------------ accounting

    def record(self, kind: str):
        """Count one injected fault (also used by external injectors like
        the agent's kill schedule)."""
        with self._lock:
            self._counts[kind] = self._counts.get(kind, 0) + 1
        c = _chaos_counter()
        if c is not None:
            c.inc(tags={"kind": kind})

    def injected_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def decision_log(self) -> List[tuple]:
        """Bounded log of (kind, method, n) triples for every injected
        fault — the artifact the determinism tests compare run-to-run."""
        with self._lock:
            return list(self._log)


# ---------------------------------------------------------------------------
# Process-wide singleton
# ---------------------------------------------------------------------------

_UNSET = object()
_injector: Any = _UNSET
_injector_lock = threading.Lock()


def _build_from_config() -> Optional[FaultInjector]:
    try:
        cfg = get_config()
    except Exception:
        return None
    spec: Optional[dict] = None
    if getattr(cfg, "chaos_spec", ""):
        try:
            spec = json.loads(cfg.chaos_spec)
        except (ValueError, TypeError):
            spec = None
    if cfg.chaos_rpc_delay_ms > 0.0:
        # Back-compat: the original single-knob harness is now just a
        # one-rule spec on the same injector.
        spec = dict(spec or {})
        spec.setdefault("rules", []).append(
            {"kind": "delay", "ms": cfg.chaos_rpc_delay_ms})
    if not spec or (not spec.get("rules") and not spec.get("kills")):
        return None
    return FaultInjector(spec)


def injector() -> Optional[FaultInjector]:
    """The process's installed injector (None = chaos disabled; the hot
    path pays one global check).  Lazily built from config/env on first
    use; replaced at runtime by :func:`install`."""
    global _injector
    if _injector is _UNSET:
        with _injector_lock:
            if _injector is _UNSET:
                _injector = _build_from_config()
    return _injector


def install(spec: Any) -> Optional[FaultInjector]:
    """Install (or, with a falsy/empty spec, clear) the runtime chaos spec
    for this process.  A runtime install overrides the config/env spec.

    Idempotent per spec: re-installing the SAME spec keeps the existing
    injector (and its counters/decision log).  The broadcast plane
    converges through several channels — pubsub, heartbeat piggyback,
    agent->worker forward — and in-process multi-agent clusters share one
    injector, so the second delivery of one chaos_set must not wipe the
    faults the first already recorded."""
    global _injector
    with _injector_lock:
        if isinstance(spec, str):
            spec = json.loads(spec) if spec.strip() else {}
        if not spec or (not spec.get("rules") and not spec.get("kills")):
            _injector = None
        elif (isinstance(_injector, FaultInjector)
                and _injector.spec == dict(spec)):
            pass  # same spec re-delivered: keep counters + decision log
        else:
            _injector = FaultInjector(spec)
        return _injector


def reset():
    """Forget the installed injector so the next :func:`injector` call
    re-derives from config/env — called by ``shutdown()`` alongside
    ``reset_config()``."""
    global _injector
    with _injector_lock:
        _injector = _UNSET
