"""Serialization: cloudpickle for code, pickle-5 out-of-band buffers for data.

The reference splits serialization the same way (``python/ray/_private/serialization.py``):
cloudpickle for closures/classes shipped through the function registry, and a zero-copy
buffer protocol (Arrow / pickle5) for array payloads so large tensors move as raw bytes
into the object store without an extra copy.  Here the out-of-band buffers are what lands
in the shared-memory store; deserialization reconstructs numpy arrays as views over the
store's mmap when possible.

ObjectRefs found inside arguments are collected during serialization (for dependency
tracking) exactly like the reference's ``SerializationContext`` does with
``_postprocess_serialized_object``.
"""

from __future__ import annotations

import io
import os
import pickle
import sys
import sysconfig
import types
from typing import Any, List, Tuple

import cloudpickle
from cloudpickle.cloudpickle import _dynamic_class_reduce

_copy_stats = None


def _stats():
    """ray_tpu.util.metrics.copy_stats, imported lazily (core <-> util
    import cycle) and cached."""
    global _copy_stats
    if _copy_stats is None:
        from ray_tpu.util.metrics import copy_stats
        _copy_stats = copy_stats
    return _copy_stats

# Roots under which a module is assumed importable on every worker: the
# interpreter's stdlib + site-packages, and this package itself (workers get
# the package root on PYTHONPATH — node_agent._spawn_worker).  Functions and
# classes defined anywhere else (driver scripts, test files, notebook dirs)
# are shipped BY VALUE, matching the reference's function-table export which
# pickles the def itself rather than a module path
# (python/ray/_private/function_manager.py export/fetch), so workers never
# need the driver's cwd or sys.path to run ``Pool.map(module_fn)``.
_PORTABLE_ROOTS = tuple(
    os.path.abspath(p) + os.sep
    for p in {
        sysconfig.get_paths().get("stdlib", ""),
        sysconfig.get_paths().get("platstdlib", ""),
        sysconfig.get_paths().get("purelib", ""),
        sysconfig.get_paths().get("platlib", ""),
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),  # ray_tpu/
    }
    if p
)


def _ship_by_value(obj) -> bool:
    """True when ``obj``'s defining module may not be importable on workers."""
    mod_name = getattr(obj, "__module__", None)
    if mod_name is None or mod_name == "__main__":
        return False  # cloudpickle already pickles __main__ defs by value
    mod = sys.modules.get(mod_name)
    if mod is None:
        return False
    mod_file = getattr(mod, "__file__", None)
    if mod_file is None:
        return False  # builtin / frozen — always importable
    mod_file = os.path.abspath(mod_file)
    return not mod_file.startswith(_PORTABLE_ROOTS)


class _ByValuePickler(cloudpickle.CloudPickler):
    """CloudPickler that forces by-value pickling for non-portable defs."""

    def reducer_override(self, obj):
        if isinstance(obj, types.FunctionType) and _ship_by_value(obj):
            return self._dynamic_function_reduce(obj)
        if isinstance(obj, type) and _ship_by_value(obj):
            return _dynamic_class_reduce(obj)
        return super().reducer_override(obj)


class SerializedObject:
    """A picked value split into a metadata stream + zero-copy buffers.

    Two-phase layout (the scatter-gather put): phase one is the pickle-5
    ``buffer_callback`` pass in :func:`serialize`, which produces the inband
    stream plus out-of-band :class:`pickle.PickleBuffer` views over the
    ORIGINAL payload memory (no copy); phase two is :meth:`write_into`,
    which lays header + inband + buffers directly into an arena-allocated
    store mapping — the payload's single host copy.  :meth:`to_bytes` (a
    full flatten through an intermediate ``bytes``) exists for small inline
    values and RPC blobs only; on large payloads it records a
    ``serialize_flatten`` copy event, which the copy-discipline tests pin
    at zero for the put path.
    """

    __slots__ = ("inband", "buffers", "contained_refs", "_header", "_sizes")

    def __init__(self, inband: bytes, buffers: List[pickle.PickleBuffer | memoryview | bytes],
                 contained_refs: list):
        self.inband = inband
        self.buffers = buffers
        self.contained_refs = contained_refs
        self._header: bytes | None = None
        self._sizes: list[int] | None = None

    def total_bytes(self) -> int:
        return len(self.inband) + sum(len(memoryview(b).cast("B")) for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten to one contiguous byte string: header + inband + buffers."""
        header, sizes = self.header_and_sizes()
        payload = sum(sizes)
        _stats().record("serialize_flatten", payload)
        out = io.BytesIO()
        out.write(len(header).to_bytes(4, "big"))
        out.write(header)
        out.write(self.inband)
        for b in self.buffers:
            out.write(memoryview(b).cast("B"))
        return out.getvalue()

    def header_and_sizes(self) -> tuple[bytes, list[int]]:
        # Cached: flat_size() + write_into() both need it, and the header
        # must be byte-identical between the sizing and writing phases.
        if self._header is None:
            self._sizes = [len(self.inband)] + [
                len(memoryview(b).cast("B")) for b in self.buffers]
            self._header = pickle.dumps(self._sizes, protocol=5)
        return self._header, self._sizes

    def flat_size(self) -> int:
        header, sizes = self.header_and_sizes()
        return 4 + len(header) + sum(sizes)

    def write_into(self, view: memoryview) -> int:
        """Serialize directly into a writable buffer (e.g. a store mmap).

        This is the put path's ONE data copy: buffers stream from the
        caller's memory straight into the arena mapping.  Recorded as a
        single ``object_write`` copy event regardless of buffer count."""
        header, sizes = self.header_and_sizes()
        off = 0
        view[0:4] = len(header).to_bytes(4, "big")
        off = 4
        view[off:off + len(header)] = header
        off += len(header)
        for part in [self.inband] + self.buffers:
            mv = memoryview(part).cast("B")
            view[off:off + len(mv)] = mv
            off += len(mv)
        _stats().record("object_write", sum(sizes))
        return off

    @classmethod
    def from_buffer(cls, buf) -> "SerializedObject":
        """Reconstruct from a flattened buffer (zero-copy views into ``buf``)."""
        mv = memoryview(buf)
        hlen = int.from_bytes(bytes(mv[:4]), "big")
        sizes = pickle.loads(bytes(mv[4:4 + hlen]))
        off = 4 + hlen
        parts = []
        for s in sizes:
            parts.append(mv[off:off + s])
            off += s
        return cls(bytes(parts[0]), list(parts[1:]), [])


class _RefPickler(_ByValuePickler):
    """cloudpickle + ObjectRef interception: refs found inside the value are
    collected into ``self.contained`` (for dependency/borrow tracking) and
    replaced by persistent ids.  protocol 5 gives out-of-band buffer
    extraction for numpy and friends."""

    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)
        self.contained: list = []

    def persistent_id(self, obj):
        from .object_ref import ObjectRef  # local import to break cycle
        if isinstance(obj, ObjectRef):
            self.contained.append(obj)
            return ("rayref", obj.id.binary(), obj.owner)
        return None


class _RefUnpickler(pickle.Unpickler):
    def persistent_load(self, pid):
        tag, idbin, owner = pid
        if tag != "rayref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag}")
        from .ids import ObjectID
        from .object_ref import ObjectRef
        return ObjectRef(ObjectID(idbin), owner=owner)


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def _collect(pb: pickle.PickleBuffer) -> bool:
        buffers.append(pb)
        return False  # out-of-band

    sio = io.BytesIO()
    p = _RefPickler(sio, buffer_callback=_collect)
    p.dump(value)
    return SerializedObject(sio.getvalue(), buffers, p.contained)


def _attach_lease(buffers: list, lease) -> list:
    """Wrap raw store views in lease-carrying buffer exporters.

    The exporter must be the object the view chain's ROOT keeps alive, and
    it must not be an ndarray: numpy collapses ndarray base chains (a view
    of a view points at the ultimate owner), so a lease hung on an
    intermediate array is dropped the moment numpy re-wraps the buffer.  A
    ctypes array ``from_buffer`` over the mapping survives as the root
    memoryview's ``obj`` for every downstream view, releasing the lease —
    and with it the store pin — exactly when the LAST deserialized view
    dies, by plain refcounting.  The array type is built with ``type()``
    rather than ``c_char * n`` so it dies with the instance instead of
    accumulating in ctypes' permanent per-length type cache.  Views are
    handed out READONLY: they alias shared (possibly same-host-broadcast)
    store pages."""
    import ctypes
    wrapped = []
    for b in buffers:
        mv = memoryview(b)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        arr_t = type("_LeasedExport", (ctypes.Array,),
                     {"_type_": ctypes.c_char, "_length_": len(mv)})
        exporter = arr_t.from_buffer(mv)
        exporter._pin_lease = lease
        wrapped.append(memoryview(exporter).toreadonly())
    return wrapped


def deserialize(so: SerializedObject, pin_lease=None) -> Any:
    """Deserialize; with ``pin_lease`` the out-of-band buffers stay
    ZERO-COPY views over the (pinned) store mapping, and the pin releases
    when the last reconstructed view is garbage-collected.  Without a
    lease, buffers are consumed as-is (inline records, copied fetches)."""
    buffers = so.buffers
    if pin_lease is not None:
        if buffers:
            buffers = _attach_lease(buffers, pin_lease)
        else:
            # Whole value lives in the (copied) inband stream: nothing will
            # ever reference the mapping — release the pin now.
            pin_lease.release()
    return _RefUnpickler(io.BytesIO(so.inband), buffers=buffers).load()


def dumps(value: Any) -> bytes:
    """One-shot flat serialize (for RPC payloads, function registry)."""
    return serialize(value).to_bytes()


def loads(data) -> Any:
    return deserialize(SerializedObject.from_buffer(data))


_NONE_BYTES: bytes | None = None


def none_bytes() -> bytes:
    """Canonical flat serialization of ``None`` — the single most common task
    result.  Producers emit this exact blob and consumers match it by bytes
    equality, skipping a pickler round trip on both sides."""
    global _NONE_BYTES
    if _NONE_BYTES is None:
        _NONE_BYTES = serialize(None).to_bytes()
    return _NONE_BYTES


def dumps_function(fn) -> bytes:
    return dumps_function_with_refs(fn)[0]


def dumps_function_with_refs(fn) -> Tuple[bytes, list]:
    """Serialize a function/class AND report the ObjectRefs captured in its
    closure/defaults.  Captured refs are real data dependencies — the
    submitter must treat them like argument refs (pin them, and never batch
    the consumer with the producer), or a closure-captured ref can deadlock
    an intra-batch dependency."""
    sio = io.BytesIO()
    p = _RefPickler(sio, buffer_callback=None)
    p.dump(fn)
    return sio.getvalue(), p.contained


def loads_function(data: bytes):
    # _RefUnpickler: function blobs may contain persistent-id'd ObjectRefs
    # (closure captures) recorded by dumps_function_with_refs.
    return _RefUnpickler(io.BytesIO(data)).load()
