"""Serialization: cloudpickle for code, pickle-5 out-of-band buffers for data.

The reference splits serialization the same way (``python/ray/_private/serialization.py``):
cloudpickle for closures/classes shipped through the function registry, and a zero-copy
buffer protocol (Arrow / pickle5) for array payloads so large tensors move as raw bytes
into the object store without an extra copy.  Here the out-of-band buffers are what lands
in the shared-memory store; deserialization reconstructs numpy arrays as views over the
store's mmap when possible.

ObjectRefs found inside arguments are collected during serialization (for dependency
tracking) exactly like the reference's ``SerializationContext`` does with
``_postprocess_serialized_object``.
"""

from __future__ import annotations

import io
import os
import pickle
import sys
import sysconfig
import types
from typing import Any, List, Tuple

import cloudpickle
from cloudpickle.cloudpickle import _dynamic_class_reduce

# Roots under which a module is assumed importable on every worker: the
# interpreter's stdlib + site-packages, and this package itself (workers get
# the package root on PYTHONPATH — node_agent._spawn_worker).  Functions and
# classes defined anywhere else (driver scripts, test files, notebook dirs)
# are shipped BY VALUE, matching the reference's function-table export which
# pickles the def itself rather than a module path
# (python/ray/_private/function_manager.py export/fetch), so workers never
# need the driver's cwd or sys.path to run ``Pool.map(module_fn)``.
_PORTABLE_ROOTS = tuple(
    os.path.abspath(p) + os.sep
    for p in {
        sysconfig.get_paths().get("stdlib", ""),
        sysconfig.get_paths().get("platstdlib", ""),
        sysconfig.get_paths().get("purelib", ""),
        sysconfig.get_paths().get("platlib", ""),
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),  # ray_tpu/
    }
    if p
)


def _ship_by_value(obj) -> bool:
    """True when ``obj``'s defining module may not be importable on workers."""
    mod_name = getattr(obj, "__module__", None)
    if mod_name is None or mod_name == "__main__":
        return False  # cloudpickle already pickles __main__ defs by value
    mod = sys.modules.get(mod_name)
    if mod is None:
        return False
    mod_file = getattr(mod, "__file__", None)
    if mod_file is None:
        return False  # builtin / frozen — always importable
    mod_file = os.path.abspath(mod_file)
    return not mod_file.startswith(_PORTABLE_ROOTS)


class _ByValuePickler(cloudpickle.CloudPickler):
    """CloudPickler that forces by-value pickling for non-portable defs."""

    def reducer_override(self, obj):
        if isinstance(obj, types.FunctionType) and _ship_by_value(obj):
            return self._dynamic_function_reduce(obj)
        if isinstance(obj, type) and _ship_by_value(obj):
            return _dynamic_class_reduce(obj)
        return super().reducer_override(obj)


class SerializedObject:
    """A picked value split into a metadata stream + zero-copy buffers."""

    __slots__ = ("inband", "buffers", "contained_refs")

    def __init__(self, inband: bytes, buffers: List[pickle.PickleBuffer | memoryview | bytes],
                 contained_refs: list):
        self.inband = inband
        self.buffers = buffers
        self.contained_refs = contained_refs

    def total_bytes(self) -> int:
        return len(self.inband) + sum(len(memoryview(b).cast("B")) for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten to one contiguous byte string: header + inband + buffers."""
        parts = [self.inband] + [bytes(memoryview(b).cast("B")) for b in self.buffers]
        header = pickle.dumps([len(p) for p in parts], protocol=5)
        out = io.BytesIO()
        out.write(len(header).to_bytes(4, "big"))
        out.write(header)
        for p in parts:
            out.write(p)
        return out.getvalue()

    def header_and_sizes(self) -> tuple[bytes, list[int]]:
        sizes = [len(self.inband)] + [len(memoryview(b).cast("B")) for b in self.buffers]
        header = pickle.dumps(sizes, protocol=5)
        return header, sizes

    def flat_size(self) -> int:
        header, sizes = self.header_and_sizes()
        return 4 + len(header) + sum(sizes)

    def write_into(self, view: memoryview) -> int:
        """Serialize directly into a writable buffer (e.g. a store mmap)."""
        header, sizes = self.header_and_sizes()
        off = 0
        view[0:4] = len(header).to_bytes(4, "big")
        off = 4
        view[off:off + len(header)] = header
        off += len(header)
        for part in [self.inband] + self.buffers:
            mv = memoryview(part).cast("B")
            view[off:off + len(mv)] = mv
            off += len(mv)
        return off

    @classmethod
    def from_buffer(cls, buf) -> "SerializedObject":
        """Reconstruct from a flattened buffer (zero-copy views into ``buf``)."""
        mv = memoryview(buf)
        hlen = int.from_bytes(bytes(mv[:4]), "big")
        sizes = pickle.loads(bytes(mv[4:4 + hlen]))
        off = 4 + hlen
        parts = []
        for s in sizes:
            parts.append(mv[off:off + s])
            off += s
        return cls(bytes(parts[0]), list(parts[1:]), [])


class _RefPickler(_ByValuePickler):
    """cloudpickle + ObjectRef interception: refs found inside the value are
    collected into ``self.contained`` (for dependency/borrow tracking) and
    replaced by persistent ids.  protocol 5 gives out-of-band buffer
    extraction for numpy and friends."""

    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)
        self.contained: list = []

    def persistent_id(self, obj):
        from .object_ref import ObjectRef  # local import to break cycle
        if isinstance(obj, ObjectRef):
            self.contained.append(obj)
            return ("rayref", obj.id.binary(), obj.owner)
        return None


class _RefUnpickler(pickle.Unpickler):
    def persistent_load(self, pid):
        tag, idbin, owner = pid
        if tag != "rayref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag}")
        from .ids import ObjectID
        from .object_ref import ObjectRef
        return ObjectRef(ObjectID(idbin), owner=owner)


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def _collect(pb: pickle.PickleBuffer) -> bool:
        buffers.append(pb)
        return False  # out-of-band

    sio = io.BytesIO()
    p = _RefPickler(sio, buffer_callback=_collect)
    p.dump(value)
    return SerializedObject(sio.getvalue(), buffers, p.contained)


def deserialize(so: SerializedObject) -> Any:
    return _RefUnpickler(io.BytesIO(so.inband), buffers=so.buffers).load()


def dumps(value: Any) -> bytes:
    """One-shot flat serialize (for RPC payloads, function registry)."""
    return serialize(value).to_bytes()


def loads(data) -> Any:
    return deserialize(SerializedObject.from_buffer(data))


_NONE_BYTES: bytes | None = None


def none_bytes() -> bytes:
    """Canonical flat serialization of ``None`` — the single most common task
    result.  Producers emit this exact blob and consumers match it by bytes
    equality, skipping a pickler round trip on both sides."""
    global _NONE_BYTES
    if _NONE_BYTES is None:
        _NONE_BYTES = serialize(None).to_bytes()
    return _NONE_BYTES


def dumps_function(fn) -> bytes:
    return dumps_function_with_refs(fn)[0]


def dumps_function_with_refs(fn) -> Tuple[bytes, list]:
    """Serialize a function/class AND report the ObjectRefs captured in its
    closure/defaults.  Captured refs are real data dependencies — the
    submitter must treat them like argument refs (pin them, and never batch
    the consumer with the producer), or a closure-captured ref can deadlock
    an intra-batch dependency."""
    sio = io.BytesIO()
    p = _RefPickler(sio, buffer_callback=None)
    p.dump(fn)
    return sio.getvalue(), p.contained


def loads_function(data: bytes):
    # _RefUnpickler: function blobs may contain persistent-id'd ObjectRefs
    # (closure captures) recorded by dumps_function_with_refs.
    return _RefUnpickler(io.BytesIO(data)).load()
