"""Serialization: cloudpickle for code, pickle-5 out-of-band buffers for data.

The reference splits serialization the same way (``python/ray/_private/serialization.py``):
cloudpickle for closures/classes shipped through the function registry, and a zero-copy
buffer protocol (Arrow / pickle5) for array payloads so large tensors move as raw bytes
into the object store without an extra copy.  Here the out-of-band buffers are what lands
in the shared-memory store; deserialization reconstructs numpy arrays as views over the
store's mmap when possible.

ObjectRefs found inside arguments are collected during serialization (for dependency
tracking) exactly like the reference's ``SerializationContext`` does with
``_postprocess_serialized_object``.
"""

from __future__ import annotations

import io
import os
import pickle
import sys
import sysconfig
import types
from typing import Any, List, Tuple

import cloudpickle
from cloudpickle.cloudpickle import _dynamic_class_reduce

_copy_stats = None


def _stats():
    """ray_tpu.util.metrics.copy_stats, imported lazily (core <-> util
    import cycle) and cached."""
    global _copy_stats
    if _copy_stats is None:
        from ray_tpu.util.metrics import copy_stats
        _copy_stats = copy_stats
    return _copy_stats

# Roots under which a module is assumed importable on every worker: the
# interpreter's stdlib + site-packages, and this package itself (workers get
# the package root on PYTHONPATH — node_agent._spawn_worker).  Functions and
# classes defined anywhere else (driver scripts, test files, notebook dirs)
# are shipped BY VALUE, matching the reference's function-table export which
# pickles the def itself rather than a module path
# (python/ray/_private/function_manager.py export/fetch), so workers never
# need the driver's cwd or sys.path to run ``Pool.map(module_fn)``.
_PORTABLE_ROOTS = tuple(
    os.path.abspath(p) + os.sep
    for p in {
        sysconfig.get_paths().get("stdlib", ""),
        sysconfig.get_paths().get("platstdlib", ""),
        sysconfig.get_paths().get("purelib", ""),
        sysconfig.get_paths().get("platlib", ""),
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),  # ray_tpu/
    }
    if p
)


def _ship_by_value(obj) -> bool:
    """True when ``obj``'s defining module may not be importable on workers."""
    mod_name = getattr(obj, "__module__", None)
    if mod_name is None or mod_name == "__main__":
        return False  # cloudpickle already pickles __main__ defs by value
    mod = sys.modules.get(mod_name)
    if mod is None:
        return False
    mod_file = getattr(mod, "__file__", None)
    if mod_file is None:
        return False  # builtin / frozen — always importable
    mod_file = os.path.abspath(mod_file)
    return not mod_file.startswith(_PORTABLE_ROOTS)


class _ByValuePickler(cloudpickle.CloudPickler):
    """CloudPickler that forces by-value pickling for non-portable defs."""

    def reducer_override(self, obj):
        if isinstance(obj, types.FunctionType) and _ship_by_value(obj):
            return self._dynamic_function_reduce(obj)
        if isinstance(obj, type) and _ship_by_value(obj):
            return _dynamic_class_reduce(obj)
        return super().reducer_override(obj)


class SerializedObject:
    """A picked value split into a metadata stream + zero-copy buffers.

    Two-phase layout (the scatter-gather put): phase one is the pickle-5
    ``buffer_callback`` pass in :func:`serialize`, which produces the inband
    stream plus out-of-band :class:`pickle.PickleBuffer` views over the
    ORIGINAL payload memory (no copy); phase two is :meth:`write_into`,
    which lays header + inband + buffers directly into an arena-allocated
    store mapping — the payload's single host copy.  :meth:`to_bytes` (a
    full flatten through an intermediate ``bytes``) exists for small inline
    values and RPC blobs only; on large payloads it records a
    ``serialize_flatten`` copy event, which the copy-discipline tests pin
    at zero for the put path.
    """

    __slots__ = ("inband", "buffers", "contained_refs", "_header", "_sizes")

    def __init__(self, inband: bytes, buffers: List[pickle.PickleBuffer | memoryview | bytes],
                 contained_refs: list):
        self.inband = inband
        self.buffers = buffers
        self.contained_refs = contained_refs
        self._header: bytes | None = None
        self._sizes: list[int] | None = None

    def total_bytes(self) -> int:
        return len(self.inband) + sum(len(memoryview(b).cast("B")) for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten to one contiguous byte string: header + inband + buffers."""
        header, sizes = self.header_and_sizes()
        payload = sum(sizes)
        _stats().record("serialize_flatten", payload)
        out = io.BytesIO()
        out.write(len(header).to_bytes(4, "big"))
        out.write(header)
        out.write(self.inband)
        for b in self.buffers:
            out.write(memoryview(b).cast("B"))
        return out.getvalue()

    def header_and_sizes(self) -> tuple[bytes, list[int]]:
        # Cached: flat_size() + write_into() both need it, and the header
        # must be byte-identical between the sizing and writing phases.
        if self._header is None:
            self._sizes = [len(self.inband)] + [
                len(memoryview(b).cast("B")) for b in self.buffers]
            self._header = pickle.dumps(self._sizes, protocol=5)
        return self._header, self._sizes

    def flat_size(self) -> int:
        header, sizes = self.header_and_sizes()
        return 4 + len(header) + sum(sizes)

    def write_into(self, view: memoryview) -> int:
        """Serialize directly into a writable buffer (e.g. a store mmap).

        This is the put path's ONE data copy: buffers stream from the
        caller's memory straight into the arena mapping.  Recorded as a
        single ``object_write`` copy event regardless of buffer count."""
        header, sizes = self.header_and_sizes()
        off = 0
        view[0:4] = len(header).to_bytes(4, "big")
        off = 4
        view[off:off + len(header)] = header
        off += len(header)
        for part in [self.inband] + self.buffers:
            mv = memoryview(part).cast("B")
            view[off:off + len(mv)] = mv
            off += len(mv)
        _stats().record("object_write", sum(sizes))
        return off

    @classmethod
    def from_buffer(cls, buf) -> "SerializedObject":
        """Reconstruct from a flattened buffer (zero-copy views into ``buf``).

        Two layouts parse here: the classic sequential one
        (``[4B hlen][header=pickle(sizes)][inband][buffers...]``) and the
        zero-copy put's reserve-then-write layout, whose header is a dict
        in a fixed padded region and whose BUFFERS precede the inband
        stream (they land during the pickle dump, before the stream's
        final size is known — see :func:`serialize_into`)."""
        mv = memoryview(buf)
        hlen = int.from_bytes(bytes(mv[:4]), "big")
        header = pickle.loads(bytes(mv[4:4 + hlen]))
        off = 4 + hlen
        if isinstance(header, dict):
            # reserve-then-write layout: buffers first, inband last
            sizes = header["sizes"]
            bufs = []
            for s in sizes[1:]:
                bufs.append(mv[off:off + s])
                off += s
            return cls(bytes(mv[off:off + sizes[0]]), bufs, [])
        parts = []
        for s in header:
            parts.append(mv[off:off + s])
            off += s
        return cls(bytes(parts[0]), list(parts[1:]), [])


class _RefPickler(_ByValuePickler):
    """cloudpickle + ObjectRef interception: refs found inside the value are
    collected into ``self.contained`` (for dependency/borrow tracking) and
    replaced by persistent ids.  protocol 5 gives out-of-band buffer
    extraction for numpy and friends."""

    def __init__(self, file, buffer_callback):
        super().__init__(file, protocol=5, buffer_callback=buffer_callback)
        self.contained: list = []

    def persistent_id(self, obj):
        from .object_ref import ObjectRef  # local import to break cycle
        if isinstance(obj, ObjectRef):
            self.contained.append(obj)
            return ("rayref", obj.id.binary(), obj.owner)
        return None


class _RefUnpickler(pickle.Unpickler):
    def persistent_load(self, pid):
        tag, idbin, owner = pid
        if tag != "rayref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag}")
        from .ids import ObjectID
        from .object_ref import ObjectRef
        return ObjectRef(ObjectID(idbin), owner=owner)


def serialize(value: Any) -> SerializedObject:
    buffers: List[pickle.PickleBuffer] = []

    def _collect(pb: pickle.PickleBuffer) -> bool:
        buffers.append(pb)
        return False  # out-of-band

    sio = io.BytesIO()
    p = _RefPickler(sio, buffer_callback=_collect)
    p.dump(value)
    return SerializedObject(sio.getvalue(), buffers, p.contained)


# ---------------------------------------------------------------------------
# Zero-copy put: reserve-then-write serialization (serialize INTO an arena
# range instead of serialize-then-copy).
#
# The classic large-put pipeline is serialize() -> store_create -> one
# write_into memcpy: the payload is materialized once into the arena by a
# single thread, which PROFILE_CORE round 6 measured at ~78% of the box's
# single-thread memcpy ceiling — the whole put is bounded by that one
# memcpy.  Reserve-then-write removes it as a *separate, serial* stage:
#
#   1. estimate_flat_size() upper-bounds the flat encoding from the
#      value's buffer-protocol payload (no pickling);
#   2. the caller reserves an arena range of that size (store_create);
#   3. serialize_into() pickles straight at the reservation: out-of-band
#      buffers are assigned arena offsets as the pickler surfaces them
#      and then land by parallel memoryview gather-write (numpy copyto
#      stripes release the GIL, so big buffers land at aggregate memory
#      bandwidth, not the single-thread ceiling), the inband stream and
#      the padded header follow, and seal happens in place;
#   4. an estimate MISS (encoding outgrew the reservation, too many
#      buffers for the header region, payload not buffer-dominated)
#      raises _EstimateMiss and the caller falls back to the classic
#      1-copy path — correctness never depends on the estimate.
#
# No payload byte is ever materialized outside its source and the arena
# (the plasma/Arrow zero-copy-put convention: serialization targets store
# memory directly), which is what the copy ledger's put/copies=0 class
# declares.  Bytes still traverse the memory bus once — physics — but
# there is no intermediate bytes object and no serial post-hoc memcpy.

#: fixed padded header region of the reserve-then-write layout: the real
#: header (a dict with the part sizes) is backpatched here after the dump
#: and padded with zero bytes, which pickle.loads ignores past STOP.
ZC_HEADER_RESERVE = 4096
#: buffers at or above this stripe over the gather pool; smaller ones are
#: landed inline by the dumping thread (thread dispatch would cost more)
_GATHER_MIN_BUF = 4 << 20
#: minimum bytes of buffer-protocol payload per gather stripe
_GATHER_MIN_STRIPE = 2 << 20


class _EstimateMiss(Exception):
    """The reserve-then-write encoding outgrew its reservation (or the
    value's shape defeated the estimator mid-dump): fall back to the
    classic serialize-then-copy path."""


class SerializedInto:
    """Result of a completed :func:`serialize_into`: the metadata the put
    path needs (the bytes already live in the arena view)."""

    __slots__ = ("used", "payload_bytes", "contained_refs", "num_buffers")

    def __init__(self, used: int, payload_bytes: int, contained_refs: list,
                 num_buffers: int):
        self.used = used
        self.payload_bytes = payload_bytes
        self.contained_refs = contained_refs
        self.num_buffers = num_buffers


def _estimate_walk(value, state: list, depth: int) -> None:
    """Accumulate (buffer_bytes, inband_bytes, nodes) for the shapes the
    estimator understands; raise _EstimateMiss for anything else."""
    state[2] += 1
    if state[2] > 10_000 or depth > 8:
        raise _EstimateMiss("value too deep/wide to estimate")
    if value is None or isinstance(value, (bool, int, float, complex)):
        state[1] += 32
        return
    if isinstance(value, (bytes, bytearray)):
        # pickle-5 keeps plain bytes/bytearray IN-BAND (only
        # buffer-protocol reducers like ndarray export out-of-band), so
        # they are inband payload: a large pure-bytes value must take
        # the classic path, not claim a zero-copy landing
        state[1] += len(value) + 64
        return
    if isinstance(value, memoryview):
        raise _EstimateMiss("raw memoryview")  # unpicklable either way
    if isinstance(value, str):
        if len(value) > 256 * 1024:
            raise _EstimateMiss("large str payload")  # utf-8 length unknown
        state[1] += 4 * len(value) + 64
        return
    tname = type(value).__module__ + "." + type(value).__name__
    if tname == "numpy.ndarray":
        # contiguous arrays export one out-of-band buffer of nbytes;
        # non-contiguous ones pickle an nbytes-sized contiguous copy
        # in-band — either way nbytes (+ dtype/shape overhead) bounds it
        if value.dtype.hasobject:
            raise _EstimateMiss("object-dtype array")
        if value.flags.c_contiguous or value.flags.f_contiguous:
            state[0] += value.nbytes
        else:
            state[1] += value.nbytes
        state[1] += 256
        return
    if isinstance(value, (list, tuple, set, frozenset)):
        state[1] += 64
        for el in value:
            _estimate_walk(el, state, depth + 1)
        return
    if isinstance(value, dict) and type(value) is dict:
        state[1] += 64
        for k, v in value.items():
            _estimate_walk(k, state, depth + 1)
            _estimate_walk(v, state, depth + 1)
        return
    raise _EstimateMiss(f"unestimable type {tname}")


def estimate_flat_size(value: Any) -> tuple[int, int] | None:
    """``(reserve, floor)`` bounds on the flat reserve-then-write encoding
    of ``value`` — ``reserve`` is the upper bound to reserve in the arena,
    ``floor`` (the raw buffer-protocol payload) is a LOWER bound of the
    exact flat size, which is what size-threshold decisions (inline vs
    plasma) must compare against: deciding on the upper bound would
    reclassify at-threshold values.  None when the value's shape is not
    one the estimator understands OR its payload is not buffer-dominated
    (zero-copy put only pays off when most bytes land out-of-band;
    inband-heavy values keep the classic path, whose single memcpy IS
    their pickle cost)."""
    state = [0, 0, 0]  # buffer_bytes, inband_bytes_upper, nodes
    try:
        _estimate_walk(value, state, 0)
    except (_EstimateMiss, RecursionError):
        return None
    buf_b, inband_b, _ = state
    if buf_b < 3 * inband_b:
        return None  # not buffer-dominated
    return 4 + ZC_HEADER_RESERVE + buf_b + inband_b + 16 * 1024, buf_b


_gather_pool = None
_gather_pool_threads = 0


def _gather_executor(threads: int):
    global _gather_pool, _gather_pool_threads
    if _gather_pool is None or _gather_pool_threads < threads:
        import concurrent.futures
        if _gather_pool is not None:
            _gather_pool.shutdown(wait=False)
        _gather_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=threads, thread_name_prefix="put-gather")
        _gather_pool_threads = threads
    return _gather_pool


def gather_threads() -> int:
    """Resolved gather-lane count (config put_gather_threads; 0 = auto)."""
    from .config import get_config
    n = get_config().put_gather_threads
    if n <= 0:
        n = min(8, os.cpu_count() or 1)
    return max(1, n)


def _land_buffer(dst: memoryview, src: memoryview, threads: int) -> None:
    """Land one out-of-band buffer into its arena slice — striped across
    the gather pool when large (numpy copyto releases the GIL per stripe,
    so the stripes run at aggregate memory bandwidth), serial otherwise.
    memoryview gather-write only: no intermediate bytes object exists on
    this path (the hot-path lint pins that)."""
    _land_batch([(dst, src)], threads)


def _land_batch(pairs: list, threads: int) -> None:
    """Land MANY (dst_view, src_view) buffers in one parallel wave: all
    stripes of all buffers go to the gather pool together, so distinct
    buffers overlap each other as well as their own stripes — landing
    N medium arrays costs one wave, not N sequential ones."""
    small, jobs = [], []
    np = None
    if threads > 1 and any(src.nbytes >= _GATHER_MIN_BUF
                           for _d, src in pairs):
        try:
            import numpy as np  # noqa: F811 — optional fast path
        except ImportError:
            np = None
    for dst, src in pairs:
        n = src.nbytes
        k = max(1, min(threads, n // _GATHER_MIN_STRIPE)) \
            if np is not None and n >= _GATHER_MIN_BUF else 1
        if k == 1:
            small.append((dst, src, n))
            continue
        d = np.frombuffer(dst, np.uint8, count=n)
        s = np.frombuffer(src, np.uint8, count=n)
        step = -(-n // k)
        for i in range(k):
            jobs.append((d, s, i * step, min(n, i * step + step)))

    def _stripe(job):
        d, s, a, b = job
        np.copyto(d[a:b], s[a:b])

    fut = (_gather_executor(threads).map(_stripe, jobs) if jobs else None)
    for dst, src, n in small:   # the dumping thread lands the small ones
        dst[:n] = src
    if fut is not None:
        list(fut)


class _ZcWriter:
    """The reserve-then-write landing state over one reserved arena view.

    The pickler writes its inband stream through :meth:`write` (buffered:
    the stream interleaves with buffer callbacks, and its final arena
    offset — after the last buffer — is only known once the dump ends);
    out-of-band buffers are assigned sequential arena offsets up front by
    :meth:`land` and copied straight source -> arena.  ``finish``
    appends the inband stream and backpatches the padded header."""

    __slots__ = ("view", "limit", "cursor", "sizes", "inband",
                 "payload_bytes", "threads", "deferred")

    def __init__(self, view: memoryview, threads: int):
        self.view = view
        self.limit = view.nbytes
        self.cursor = 4 + ZC_HEADER_RESERVE
        self.sizes: list[int] = []          # buffer sizes, in land order
        self.inband = io.BytesIO()
        self.payload_bytes = 0
        self.threads = threads
        #: large buffers deferred to one batched parallel landing: the
        #: pool then overlaps DISTINCT buffers too, not just stripes
        self.deferred: list[tuple[int, memoryview]] = []

    def write(self, b) -> int:
        return self.inband.write(b)

    def land(self, pb: pickle.PickleBuffer) -> bool:
        """pickle-5 buffer_callback: claim the next arena range for this
        buffer.  Returns False (out-of-band) on success; raises on a
        reservation overflow so the dump aborts immediately."""
        try:
            raw = pb.raw()
        except Exception:
            return True  # non-contiguous: let pickle serialize it in-band
        if raw.format != "B" or raw.ndim != 1:
            raw = raw.cast("B")
        n = raw.nbytes
        if self.cursor + n > self.limit:
            raise _EstimateMiss(f"buffer overflows reservation "
                                f"({self.cursor + n} > {self.limit})")
        if len(self.sizes) >= 256:
            raise _EstimateMiss("too many buffers for the header region")
        off = self.cursor
        self.cursor += n
        self.sizes.append(n)
        self.payload_bytes += n
        if n >= _GATHER_MIN_BUF and self.threads > 1:
            self.deferred.append((off, raw))
        else:
            self.view[off:off + n] = raw
        return False

    def finish(self, contained_refs: list) -> SerializedInto:
        inband = self.inband.getbuffer()
        ilen = inband.nbytes
        if self.cursor + ilen > self.limit:
            raise _EstimateMiss("inband stream overflows reservation")
        header = pickle.dumps({"sizes": [ilen] + self.sizes}, protocol=5)
        if 4 + len(header) > 4 + ZC_HEADER_RESERVE:
            raise _EstimateMiss("header overflows its reserved region")
        if self.deferred:
            _land_batch([(self.view[off:off + raw.nbytes], raw)
                         for off, raw in self.deferred], self.threads)
        self.view[self.cursor:self.cursor + ilen] = inband
        used = self.cursor + ilen
        self.view[0:4] = ZC_HEADER_RESERVE.to_bytes(4, "big")
        self.view[4:4 + len(header)] = header
        pad_end = 4 + ZC_HEADER_RESERVE
        self.view[4 + len(header):pad_end] = \
            b"\x00" * (pad_end - 4 - len(header))  # inert past pickle STOP
        _stats().record("object_write_direct", self.payload_bytes + ilen)
        return SerializedInto(used, self.payload_bytes, contained_refs,
                              len(self.sizes))


def serialize_into(value: Any, view: memoryview) -> SerializedInto | None:
    """Serialize ``value`` DIRECTLY into the reserved arena ``view``
    (reserve-then-write; see the module section comment).  Returns the
    landing metadata, or None on a size-estimate miss — the caller falls
    back to the classic serialize-then-copy path; nothing useful is in
    ``view`` after a miss."""
    w = _ZcWriter(view, gather_threads())
    try:
        p = _RefPickler(w, buffer_callback=w.land)
        p.dump(value)
        return w.finish(p.contained)
    except _EstimateMiss:
        return None


def _attach_lease(buffers: list, lease) -> list:
    """Wrap raw store views in lease-carrying buffer exporters.

    The exporter must be the object the view chain's ROOT keeps alive, and
    it must not be an ndarray: numpy collapses ndarray base chains (a view
    of a view points at the ultimate owner), so a lease hung on an
    intermediate array is dropped the moment numpy re-wraps the buffer.  A
    ctypes array ``from_buffer`` over the mapping survives as the root
    memoryview's ``obj`` for every downstream view, releasing the lease —
    and with it the store pin — exactly when the LAST deserialized view
    dies, by plain refcounting.  The array type is built with ``type()``
    rather than ``c_char * n`` so it dies with the instance instead of
    accumulating in ctypes' permanent per-length type cache.  Views are
    handed out READONLY: they alias shared (possibly same-host-broadcast)
    store pages."""
    import ctypes
    wrapped = []
    for b in buffers:
        mv = memoryview(b)
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        arr_t = type("_LeasedExport", (ctypes.Array,),
                     {"_type_": ctypes.c_char, "_length_": len(mv)})
        exporter = arr_t.from_buffer(mv)
        exporter._pin_lease = lease
        wrapped.append(memoryview(exporter).toreadonly())
    return wrapped


def deserialize(so: SerializedObject, pin_lease=None) -> Any:
    """Deserialize; with ``pin_lease`` the out-of-band buffers stay
    ZERO-COPY views over the (pinned) store mapping, and the pin releases
    when the last reconstructed view is garbage-collected.  Without a
    lease, buffers are consumed as-is (inline records, copied fetches)."""
    buffers = so.buffers
    if pin_lease is not None:
        if buffers:
            buffers = _attach_lease(buffers, pin_lease)
        else:
            # Whole value lives in the (copied) inband stream: nothing will
            # ever reference the mapping — release the pin now.
            pin_lease.release()
    return _RefUnpickler(io.BytesIO(so.inband), buffers=buffers).load()


def dumps(value: Any) -> bytes:
    """One-shot flat serialize (for RPC payloads, function registry)."""
    return serialize(value).to_bytes()


def loads(data) -> Any:
    return deserialize(SerializedObject.from_buffer(data))


_NONE_BYTES: bytes | None = None


def none_bytes() -> bytes:
    """Canonical flat serialization of ``None`` — the single most common task
    result.  Producers emit this exact blob and consumers match it by bytes
    equality, skipping a pickler round trip on both sides."""
    global _NONE_BYTES
    if _NONE_BYTES is None:
        _NONE_BYTES = serialize(None).to_bytes()
    return _NONE_BYTES


def dumps_function(fn) -> bytes:
    return dumps_function_with_refs(fn)[0]


def dumps_function_with_refs(fn) -> Tuple[bytes, list]:
    """Serialize a function/class AND report the ObjectRefs captured in its
    closure/defaults.  Captured refs are real data dependencies — the
    submitter must treat them like argument refs (pin them, and never batch
    the consumer with the producer), or a closure-captured ref can deadlock
    an intra-batch dependency."""
    sio = io.BytesIO()
    p = _RefPickler(sio, buffer_callback=None)
    p.dump(fn)
    return sio.getvalue(), p.contained


def loads_function(data: bytes):
    # _RefUnpickler: function blobs may contain persistent-id'd ObjectRefs
    # (closure captures) recorded by dumps_function_with_refs.
    return _RefUnpickler(io.BytesIO(data)).load()
