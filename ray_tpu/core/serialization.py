"""Serialization: cloudpickle for code, pickle-5 out-of-band buffers for data.

The reference splits serialization the same way (``python/ray/_private/serialization.py``):
cloudpickle for closures/classes shipped through the function registry, and a zero-copy
buffer protocol (Arrow / pickle5) for array payloads so large tensors move as raw bytes
into the object store without an extra copy.  Here the out-of-band buffers are what lands
in the shared-memory store; deserialization reconstructs numpy arrays as views over the
store's mmap when possible.

ObjectRefs found inside arguments are collected during serialization (for dependency
tracking) exactly like the reference's ``SerializationContext`` does with
``_postprocess_serialized_object``.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

import cloudpickle


class SerializedObject:
    """A picked value split into a metadata stream + zero-copy buffers."""

    __slots__ = ("inband", "buffers", "contained_refs")

    def __init__(self, inband: bytes, buffers: List[pickle.PickleBuffer | memoryview | bytes],
                 contained_refs: list):
        self.inband = inband
        self.buffers = buffers
        self.contained_refs = contained_refs

    def total_bytes(self) -> int:
        return len(self.inband) + sum(len(memoryview(b).cast("B")) for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten to one contiguous byte string: header + inband + buffers."""
        parts = [self.inband] + [bytes(memoryview(b).cast("B")) for b in self.buffers]
        header = pickle.dumps([len(p) for p in parts], protocol=5)
        out = io.BytesIO()
        out.write(len(header).to_bytes(4, "big"))
        out.write(header)
        for p in parts:
            out.write(p)
        return out.getvalue()

    def header_and_sizes(self) -> tuple[bytes, list[int]]:
        sizes = [len(self.inband)] + [len(memoryview(b).cast("B")) for b in self.buffers]
        header = pickle.dumps(sizes, protocol=5)
        return header, sizes

    def flat_size(self) -> int:
        header, sizes = self.header_and_sizes()
        return 4 + len(header) + sum(sizes)

    def write_into(self, view: memoryview) -> int:
        """Serialize directly into a writable buffer (e.g. a store mmap)."""
        header, sizes = self.header_and_sizes()
        off = 0
        view[0:4] = len(header).to_bytes(4, "big")
        off = 4
        view[off:off + len(header)] = header
        off += len(header)
        for part in [self.inband] + self.buffers:
            mv = memoryview(part).cast("B")
            view[off:off + len(mv)] = mv
            off += len(mv)
        return off

    @classmethod
    def from_buffer(cls, buf) -> "SerializedObject":
        """Reconstruct from a flattened buffer (zero-copy views into ``buf``)."""
        mv = memoryview(buf)
        hlen = int.from_bytes(bytes(mv[:4]), "big")
        sizes = pickle.loads(bytes(mv[4:4 + hlen]))
        off = 4 + hlen
        parts = []
        for s in sizes:
            parts.append(mv[off:off + s])
            off += s
        return cls(bytes(parts[0]), list(parts[1:]), [])


def serialize(value: Any) -> SerializedObject:
    contained: list = []
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(pb: pickle.PickleBuffer) -> bool:
        buffers.append(pb)
        return False  # out-of-band

    # cloudpickle handles closures/lambdas/local classes; protocol 5 gives us
    # out-of-band buffer extraction for numpy and friends.
    from .object_ref import ObjectRef  # local import to break cycle

    class _Pickler(cloudpickle.CloudPickler):
        def persistent_id(self, obj):  # intercept ObjectRefs
            if isinstance(obj, ObjectRef):
                contained.append(obj)
                return ("rayref", obj.id.binary(), obj.owner)
            return None

    sio = io.BytesIO()
    p = _Pickler(sio, protocol=5, buffer_callback=buffer_callback)
    p.dump(value)
    return SerializedObject(sio.getvalue(), buffers, contained)


def deserialize(so: SerializedObject) -> Any:
    from .object_ref import ObjectRef

    class _Unpickler(pickle.Unpickler):
        def persistent_load(self, pid):
            tag, idbin, owner = pid
            if tag != "rayref":
                raise pickle.UnpicklingError(f"unknown persistent id {tag}")
            from .ids import ObjectID
            return ObjectRef(ObjectID(idbin), owner=owner)

    return _Unpickler(io.BytesIO(so.inband), buffers=so.buffers).load()


def dumps(value: Any) -> bytes:
    """One-shot flat serialize (for RPC payloads, function registry)."""
    return serialize(value).to_bytes()


def loads(data) -> Any:
    return deserialize(SerializedObject.from_buffer(data))


def dumps_function(fn) -> bytes:
    return cloudpickle.dumps(fn)


def loads_function(data: bytes):
    return pickle.loads(data)
