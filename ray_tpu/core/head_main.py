"""Head-node daemon: GCS server + a local node agent in one process.

Reference: what ``ray start --head`` boots via ``_private/node.py:1395``
(``start_head_processes``) — GCS, raylet, and the address file other
processes discover the cluster through.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import time

ADDRESS_FILE = "/tmp/raytpu/head.json"


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", type=str, default="{}")
    p.add_argument("--labels", type=str, default="{}")
    p.add_argument("--session-dir", type=str, default="")
    p.add_argument("--object-store-memory", type=int, default=0)
    args = p.parse_args()

    from .config import Config, set_config
    cfg_json = os.environ.get("RAYTPU_CONFIG_JSON")
    if cfg_json:
        set_config(Config.from_json(cfg_json))

    from .gcs import GcsServer
    from .node_agent import NodeAgent
    from .rpc import run_async

    session_dir = args.session_dir or os.path.join(
        "/tmp/raytpu", f"head-{int(time.time() * 1000)}-{os.getpid()}")
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)

    gcs = GcsServer(session_dir=session_dir)
    run_async(gcs.start())
    agent = NodeAgent(gcs.address,
                      num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                      resources=json.loads(args.resources),
                      labels=json.loads(args.labels),
                      session_dir=session_dir,
                      object_store_memory=args.object_store_memory)
    run_async(agent.start())

    os.makedirs(os.path.dirname(ADDRESS_FILE), exist_ok=True)
    with open(ADDRESS_FILE, "w") as f:
        json.dump({"gcs_address": gcs.address, "pid": os.getpid(),
                   "session_dir": session_dir,
                   "node_id": agent.node_id.hex()}, f)
    print(json.dumps({"gcs_address": gcs.address,
                      "session_dir": session_dir}), flush=True)

    stop = False

    def _sig(*_a):
        nonlocal stop
        stop = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    while not stop:
        time.sleep(0.2)
    run_async(agent.stop(), timeout=10)
    try:
        os.unlink(ADDRESS_FILE)
    except OSError:
        pass


if __name__ == "__main__":
    main()
