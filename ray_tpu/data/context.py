"""DataContext — per-driver execution knobs for ray_tpu.data.

Reference: ``python/ray/data/context.py`` (``DataContext.get_current``): a
process-wide singleton that operators and the planner consult for target block
sizes, parallelism, and backpressure budgets.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    # Target on-disk/in-store size of one block produced by reads and maps.
    target_max_block_size: int = 128 * 1024 * 1024
    # Default minimum number of blocks a read should produce.
    read_op_min_num_blocks: int = 8
    # Streaming executor: max concurrently running tasks per operator.
    max_tasks_in_flight_per_op: int = 8
    # Streaming executor: global cap on bytes of not-yet-consumed operator
    # outputs before backpressure kicks in.
    streaming_output_backpressure_bytes: int = 1 * 1024 * 1024 * 1024
    # Actor pool defaults for Dataset.map_batches(concurrency=...) class fns.
    actor_pool_min_size: int = 1
    actor_pool_max_size: int = 4
    # Batch format handed to user fns when not specified: "numpy" | "pandas"
    # | "pyarrow".
    default_batch_format: str = "numpy"
    # Whether map tasks should eagerly release input block refs.
    eager_free: bool = True
    # Streaming-generator map tasks: downstream operators consume output
    # blocks while the producing task still runs (num_returns="streaming").
    use_streaming_generators: bool = True
    # Producer pauses after this many unconsumed streamed blocks (0 = off).
    generator_backpressure: int = 8
    # Random seed used by random_shuffle/randomize_block_order when the user
    # does not pass one (None = nondeterministic).
    seed: Optional[int] = None
    extra: dict = field(default_factory=dict)

    _instance = None
    _lock = threading.Lock()

    @staticmethod
    def get_current() -> "DataContext":
        with DataContext._lock:
            if DataContext._instance is None:
                DataContext._instance = DataContext()
            return DataContext._instance
