"""Datasources: pluggable readers/writers producing/consuming blocks.

Reference: ``python/ray/data/datasource/`` — ``Datasource.get_read_tasks`` returns
serializable ``ReadTask`` thunks that execute remotely and yield blocks;
``file_based_datasource.py`` is the shared framework for parquet/csv/json/numpy.
"""

from __future__ import annotations

import glob as globlib
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from .block import Block, BlockAccessor, BlockMetadata, VALUE_COL


@dataclass
class ReadTask:
    """A serializable zero-arg callable producing an iterable of blocks, plus
    metadata estimated at planning time (before any data is read)."""
    read_fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata

    def __call__(self) -> Iterable[Block]:
        return self.read_fn()


class Datasource:
    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


class RangeDatasource(Datasource):
    def __init__(self, n: int, *, tensor_shape: Optional[tuple] = None):
        self._n = n
        self._tensor_shape = tensor_shape

    def estimate_inmemory_data_size(self):
        per = 8 if not self._tensor_shape else 8 * int(np.prod(self._tensor_shape))
        return self._n * per

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self._n)) if self._n else 1
        tasks = []
        chunk = -(-self._n // parallelism) if self._n else 0
        shape = self._tensor_shape
        for i in range(parallelism):
            lo, hi = i * chunk, min((i + 1) * chunk, self._n)
            if lo >= hi:
                break

            def make(lo=lo, hi=hi):
                if shape is None:
                    return [pa.table({"id": pa.array(range(lo, hi), type=pa.int64())})]
                data = np.stack([np.full(shape, v, dtype=np.int64) for v in range(lo, hi)])
                return [BlockAccessor.for_block(
                    [{"data": row} for row in data]).to_arrow()]

            nbytes = (hi - lo) * (8 if shape is None else 8 * int(np.prod(shape)))
            tasks.append(ReadTask(make, BlockMetadata(num_rows=hi - lo, size_bytes=nbytes)))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = items

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self._items)
        parallelism = max(1, min(parallelism, n)) if n else 1
        chunk = -(-n // parallelism) if n else 0
        tasks = []
        for i in range(parallelism):
            part = self._items[i * chunk:(i + 1) * chunk]
            if not part:
                break

            def make(part=part):
                if part and isinstance(part[0], dict):
                    return [BlockAccessor.for_block(part).to_arrow()]
                return [part]

            tasks.append(ReadTask(make, BlockMetadata(num_rows=len(part), size_bytes=None)))
        return tasks


class BlocksDatasource(Datasource):
    """Pre-materialized in-memory blocks (from_pandas / from_arrow / from_numpy)."""

    def __init__(self, blocks: List[Block]):
        self._blocks = blocks

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for b in self._blocks:
            acc = BlockAccessor.for_block(b)
            tasks.append(ReadTask(lambda b=b: [b], acc.metadata()))
        return tasks


def _expand_paths(paths, ext: Optional[str]) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{ext}" if ext else "*")
            out.extend(sorted(f for f in globlib.glob(pat, recursive=True)
                              if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class FileBasedDatasource(Datasource):
    """Framework for path-list datasources — one or more files per read task.

    Reference: ``python/ray/data/datasource/file_based_datasource.py``.
    """

    _FILE_EXTENSION: Optional[str] = None

    def __init__(self, paths, **reader_args):
        self._paths = _expand_paths(paths, self._FILE_EXTENSION)
        self._reader_args = reader_args

    def _read_file(self, path: str) -> Iterable[Block]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self):
        try:
            return sum(os.path.getsize(p) for p in self._paths)
        except OSError:
            return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self._paths)
        parallelism = max(1, min(parallelism, n))
        per = -(-n // parallelism)
        tasks = []
        for i in range(parallelism):
            chunk = self._paths[i * per:(i + 1) * per]
            if not chunk:
                break
            read_file = self._read_file

            def make(chunk=chunk, read_file=read_file):
                def gen():
                    for p in chunk:
                        yield from read_file(p)
                return gen()

            size = None
            try:
                size = sum(os.path.getsize(p) for p in chunk)
            except OSError:
                pass
            tasks.append(ReadTask(make, BlockMetadata(num_rows=None, size_bytes=size,
                                                      input_files=chunk)))
        return tasks


class ParquetDatasource(FileBasedDatasource):
    _FILE_EXTENSION = ".parquet"

    def _read_file(self, path):
        import pyarrow.parquet as pq
        columns = self._reader_args.get("columns")
        yield pq.read_table(path, columns=columns)


class ORCDatasource(FileBasedDatasource):
    """Apache ORC columnar files via pyarrow.orc (reference:
    ``python/ray/data/read_api.py`` read_orc)."""

    _FILE_EXTENSION = ".orc"

    def _read_file(self, path):
        from pyarrow import orc as porc
        columns = self._reader_args.get("columns")
        yield porc.read_table(path, columns=columns)


class WebDatasetDatasource(FileBasedDatasource):
    """WebDataset-style tar shards: samples are groups of files sharing a
    basename (``0001.jpg`` + ``0001.cls`` -> one row with columns per
    extension) — the standard large-scale ML ingest container (reference:
    ``python/ray/data/read_api.py`` read_webdataset; stdlib tarfile, no
    webdataset dependency)."""

    _FILE_EXTENSION = ".tar"

    def _read_file(self, path):
        import tarfile

        samples: Dict[str, Dict[str, Any]] = {}
        order: List[str] = []
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                base, dot, ext = member.name.partition(".")
                if not dot:
                    base, ext = member.name, "data"
                if base not in samples:
                    samples[base] = {"__key__": base}
                    order.append(base)
                samples[base][ext] = tf.extractfile(member).read()
        rows = [samples[k] for k in order]
        if rows:
            yield BlockAccessor.for_block(rows).to_arrow()


class CSVDatasource(FileBasedDatasource):
    _FILE_EXTENSION = ".csv"

    def _read_file(self, path):
        from pyarrow import csv as pcsv
        yield pcsv.read_csv(path, **self._reader_args)


class JSONDatasource(FileBasedDatasource):
    _FILE_EXTENSION = ".json"

    def _read_file(self, path):
        from pyarrow import json as pjson
        yield pjson.read_json(path)


class NumpyDatasource(FileBasedDatasource):
    _FILE_EXTENSION = ".npy"

    def _read_file(self, path):
        arr = np.load(path, allow_pickle=False)
        yield BlockAccessor.for_block([{"data": row} for row in arr]).to_arrow()


class BinaryDatasource(FileBasedDatasource):
    _FILE_EXTENSION = None

    def _read_file(self, path):
        with open(path, "rb") as f:
            data = f.read()
        yield pa.table({"bytes": pa.array([data], type=pa.binary()),
                        "path": pa.array([path])})


class TextDatasource(FileBasedDatasource):
    _FILE_EXTENSION = None

    def _read_file(self, path):
        with open(path, "r", errors="replace") as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": pa.array(lines)})


class TFRecordDatasource(FileBasedDatasource):
    """TFRecord files of serialized ``tf.train.Example`` protos (or raw
    records with ``raw=True``).

    Reference: ``python/ray/data/datasource`` TFRecords support.  The wire
    format is parsed directly — length-delimited records with masked CRCs —
    and Example features are decoded with a minimal protobuf wire-format
    reader, so neither tensorflow nor protoc-generated stubs are needed.
    """

    _FILE_EXTENSION = None

    def _read_file(self, path):
        raw = self._reader_args.get("raw", False)
        records = list(_iter_tfrecords(path))
        if raw:
            yield pa.table({"bytes": pa.array(records, type=pa.binary())})
            return
        rows = [_parse_tf_example(r) for r in records]
        yield BlockAccessor.for_block(rows).to_arrow()


def _iter_tfrecords(path: str):
    """TFRecord framing: u64 length, u32 length-crc, payload, u32 data-crc.
    CRCs are not verified (matches the reference's default fast path)."""
    with open(path, "rb") as f:
        while True:
            head = f.read(8)
            if len(head) < 8:
                return
            (length,) = __import__("struct").unpack("<Q", head)
            f.read(4)  # length crc
            payload = f.read(length)
            if len(payload) < length:
                raise ValueError(f"truncated tfrecord in {path}")
            f.read(4)  # data crc
            yield payload


def _read_varint(buf: bytes, pos: int):
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _parse_tf_example(data: bytes) -> Dict[str, Any]:
    """Decode tf.train.Example -> {feature: value(s)} with a minimal proto
    wire reader.  Example := {1: Features{1: map<string, Feature>}};
    Feature := one of {1: BytesList, 2: FloatList, 3: Int64List}."""
    import struct

    def fields(buf):
        pos = 0
        while pos < len(buf):
            key, pos = _read_varint(buf, pos)
            tag, wire = key >> 3, key & 7
            if wire == 2:  # length-delimited
                ln, pos = _read_varint(buf, pos)
                yield tag, buf[pos:pos + ln]
                pos += ln
            elif wire == 0:
                v, pos = _read_varint(buf, pos)
                yield tag, v
            elif wire == 5:
                yield tag, buf[pos:pos + 4]
                pos += 4
            elif wire == 1:
                yield tag, buf[pos:pos + 8]
                pos += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    def parse_list(kind: int, buf: bytes):
        vals: List[Any] = []
        for tag, v in fields(buf):
            if tag != 1:
                continue
            if kind == 1:        # BytesList: repeated bytes
                vals.append(v)
            elif kind == 2:      # FloatList: packed or unpacked floats
                if isinstance(v, bytes) and len(v) == 4:
                    vals.append(struct.unpack("<f", v)[0])
                else:
                    vals.extend(struct.unpack(f"<{len(v) // 4}f", v))
            else:                # Int64List: packed or unpacked varints
                if isinstance(v, int):  # wire type 0: one unpacked element
                    vals.append(v - (1 << 64) if v >= 1 << 63 else v)
                    continue
                pos = 0
                while pos < len(v):
                    x, pos = _read_varint(v, pos)
                    vals.append(x - (1 << 64) if x >= 1 << 63 else x)
        return vals

    row: Dict[str, Any] = {}
    for tag, features in fields(data):
        if tag != 1:
            continue
        for ftag, entry in fields(features):
            if ftag != 1:
                continue
            name, feature = None, None
            for etag, v in fields(entry):
                if etag == 1:
                    name = v.decode()
                elif etag == 2:
                    feature = v
            if name is None or feature is None:
                continue
            for kind, payload in fields(feature):
                vals = parse_list(kind, payload)
                row[name] = vals[0] if len(vals) == 1 else vals
    return row


def _write_varint(out: bytearray, v: int):
    while True:
        b = v & 0x7F
        v >>= 7
        out.append(b | (0x80 if v else 0))
        if not v:
            return


def _encode_tf_example(row: Dict[str, Any]) -> bytes:
    """Inverse of _parse_tf_example (used by Dataset.write_tfrecords)."""
    import struct

    def ld(tag: int, payload: bytes) -> bytes:
        head = bytearray()
        _write_varint(head, (tag << 3) | 2)
        _write_varint(head, len(payload))
        return bytes(head) + payload

    feats = bytearray()
    for name, value in row.items():
        vals = value if isinstance(value, (list, np.ndarray)) else [value]
        inner = bytearray()
        first = vals[0] if len(vals) else 0
        if isinstance(first, (bytes, str)):
            bl = bytearray()
            for v in vals:
                bl += ld(1, v.encode() if isinstance(v, str) else bytes(v))
            inner += ld(1, bytes(bl))
        elif isinstance(first, (float, np.floating)):
            packed = struct.pack(f"<{len(vals)}f", *[float(v) for v in vals])
            inner += ld(2, ld(1, packed))
        else:
            iv = bytearray()
            for v in vals:
                _write_varint(iv, int(v) & ((1 << 64) - 1))
            inner += ld(3, ld(1, bytes(iv)))
        feats += ld(1, ld(1, name.encode()) + ld(2, bytes(inner)))
    return ld(1, bytes(feats))


_CRC32C_TABLE = None
try:  # C implementations first: the pure-Python loop is ~10 MB/s
    import crc32c as _crc32c_ext  # type: ignore
except ImportError:
    try:
        import google_crc32c as _g_crc32c  # type: ignore

        class _crc32c_ext:  # adapt to the crc32c package's call shape
            crc32c = staticmethod(lambda b: _g_crc32c.value(b))
    except ImportError:
        _crc32c_ext = None

_native_crc_state = "unloaded"  # -> callable | "failed"


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli, poly 0x1EDC6F41 reflected = 0x82F63B78).
    Prefers a pypi C extension, then our own native component
    (native/crc32c.cpp: SSE4.2 / slice-by-8, compiled lazily on the
    FIRST checksum so importing this module never spawns g++), then the
    pure-Python table loop."""
    global _native_crc_state
    if _crc32c_ext is not None:
        return _crc32c_ext.crc32c(data) & 0xFFFFFFFF
    if _native_crc_state == "unloaded":
        from ray_tpu.native import load_crc32c
        _native_crc_state = load_crc32c() or "failed"
    if _native_crc_state != "failed":
        return _native_crc_state(data) & 0xFFFFFFFF
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    tab = _CRC32C_TABLE
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc32c(data: bytes) -> int:
    """TFRecord's masked CRC (reference: tfrecords_datasource.py
    ``_masked_crc``): rotate right by 15 and add a constant."""
    crc = _crc32c(data)
    rotated = ((crc >> 15) | ((crc << 17) & 0xFFFFFFFF)) & 0xFFFFFFFF
    return (rotated + 0xA282EAD8) & 0xFFFFFFFF


def _tfrecord_frame(payload: bytes) -> bytes:
    """Frame one record with masked crc32c over the length and data fields —
    the exact wire format TF's reader verifies by default."""
    import struct
    length = struct.pack("<Q", len(payload))
    return (length + struct.pack("<I", _masked_crc32c(length))
            + payload + struct.pack("<I", _masked_crc32c(payload)))


class SQLDatasource(Datasource):
    """Rows from a SQL query via a DB-API connection factory.

    Reference: ``python/ray/data/datasource/sql_datasource.py`` — the same
    ``connection_factory + query`` contract (sqlite3 from the stdlib works
    out of the box).  Parallelism is 1 unless the caller provides
    ``shard_queries`` (DB-API has no generic cheap row-range split).
    """

    def __init__(self, sql: str, connection_factory: Callable[[], Any],
                 shard_queries: Optional[List[str]] = None):
        self._sql = sql
        self._factory = connection_factory
        self._shards = shard_queries

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        queries = self._shards or [self._sql]
        factory = self._factory

        def make(q):
            def read():
                conn = factory()
                try:
                    cur = conn.cursor()
                    cur.execute(q)
                    cols = [d[0] for d in cur.description]
                    rows = cur.fetchall()
                finally:
                    conn.close()
                if not rows:
                    return []
                table = pa.table({c: pa.array([r[i] for r in rows])
                                  for i, c in enumerate(cols)})
                return [table]
            return read

        return [ReadTask(make(q), BlockMetadata(num_rows=None,
                                                size_bytes=None))
                for q in queries]


class ImageDatasource(FileBasedDatasource):
    """Image files decoded to HWC uint8 arrays (requires PIL, present in
    most ML images; raises a clear error if absent).

    Reference: ``python/ray/data/datasource/image_datasource.py`` —
    same columns: ``image`` (ndarray) and ``path``.
    """

    _FILE_EXTENSION = None
    _EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")

    def __init__(self, paths, size: Optional[tuple] = None,
                 mode: Optional[str] = None, **kw):
        super().__init__(paths, **kw)
        self._paths = [p for p in self._paths
                       if p.lower().endswith(self._EXTS)]
        if not self._paths:
            raise FileNotFoundError(f"no image files under {paths}")
        self._size = size
        self._mode = mode

    def _read_file(self, path):
        try:
            from PIL import Image
        except ImportError as e:
            raise ImportError(
                "read_images requires pillow (PIL); not in this image"
            ) from e
        img = Image.open(path)
        if self._mode:
            img = img.convert(self._mode)
        if self._size:
            # size is (height, width) like the reference's read_images;
            # PIL's resize takes (width, height), so swap.
            img = img.resize((self._size[1], self._size[0]))
        arr = np.asarray(img)
        yield BlockAccessor.for_block(
            [{"image": arr, "path": path}]).to_arrow()


# ---------------------------------------------------------------------------
# Write path
# ---------------------------------------------------------------------------

def write_block(block: Block, path: str, file_format: str, index: int,
                **writer_args) -> str:
    os.makedirs(path, exist_ok=True)
    acc = BlockAccessor.for_block(block)
    fname = os.path.join(path, f"part-{index:06d}.{file_format}")
    if file_format == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(acc.to_arrow(), fname, **writer_args)
    elif file_format == "csv":
        from pyarrow import csv as pcsv
        pcsv.write_csv(acc.to_arrow(), fname)
    elif file_format == "json":
        df = acc.to_pandas()
        df.to_json(fname, orient="records", lines=True)
    elif file_format == "npy":
        cols = acc.to_numpy()
        key = "data" if "data" in cols else list(cols)[0]
        np.save(fname[:-4], cols[key])
    elif file_format == "orc":
        from pyarrow import orc as porc
        porc.write_table(acc.to_arrow(), fname, **writer_args)
    elif file_format == "tar":  # webdataset shard
        import io as _io
        import tarfile
        with tarfile.open(fname, "w") as tf:
            for i, row in enumerate(acc.iter_rows()):
                if not isinstance(row, dict):
                    row = {"data": row}
                key = row.get("__key__", f"{index:06d}{i:06d}")
                for ext, payload in row.items():
                    if ext == "__key__":
                        continue
                    if not isinstance(payload, bytes):
                        payload = str(payload).encode()
                    info = tarfile.TarInfo(f"{key}.{ext}")
                    info.size = len(payload)
                    tf.addfile(info, _io.BytesIO(payload))
    elif file_format == "tfrecords":
        with open(fname, "wb") as f:
            for row in acc.iter_rows():
                if not isinstance(row, dict):
                    row = {"value": row}
                f.write(_tfrecord_frame(_encode_tf_example(row)))
    else:
        raise ValueError(f"unknown write format {file_format}")
    return fname


class MongoDatasource(Datasource):
    """Documents from a MongoDB collection, partitioned by skip/limit.

    Reference: ``python/ray/data/_internal/datasource/mongo_datasource.py``
    (read_mongo/write_mongo over pymongo).  pymongo is not baked into this
    image, so the client comes from an injectable ``client_factory``
    (production: ``lambda: pymongo.MongoClient(uri)``; tests: a fake) and
    the default factory raises a clear ImportError only when actually used.
    An optional aggregation ``pipeline`` runs server-side before the
    partition window, matching the reference's pipeline argument.
    """

    def __init__(self, uri: str, database: str, collection: str,
                 pipeline: Optional[List[dict]] = None,
                 client_factory: Optional[Callable[[], Any]] = None):
        self._uri = uri
        self._db = database
        self._coll = collection
        self._pipeline = list(pipeline or [])
        self._factory = client_factory or _default_mongo_client(uri)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        factory, db, coll = self._factory, self._db, self._coll
        pipeline = self._pipeline

        def make(stages):
            def read():
                c = factory()
                try:
                    cur = c[db][coll].aggregate(stages)
                    docs = [{k: v for k, v in d.items() if k != "_id"}
                            for d in cur]
                finally:
                    _close_quietly(c)
                if not docs:
                    return []
                cols = sorted({k for d in docs for k in d})
                return [pa.table({k: pa.array([d.get(k) for d in docs])
                                  for k in cols})]
            return read

        meta = BlockMetadata(num_rows=None, size_bytes=None)
        if pipeline:
            # An aggregation pipeline can change cardinality ($unwind,
            # $group), so collection-count skip/limit windows would drop or
            # duplicate output rows — run it as ONE partition (the
            # reference partitions on _id ranges BEFORE the pipeline; that
            # needs server-side _id introspection pymongo-side).
            return [ReadTask(make(list(pipeline)), meta)]
        client = factory()
        try:
            total = client[db][coll].count_documents({})
        finally:
            _close_quietly(client)
        if total == 0:
            # empty collection: one windowless scan (MongoDB rejects
            # {"$limit": 0})
            return [ReadTask(make([]), meta)]
        parallelism = max(1, min(parallelism if parallelism > 0 else 8,
                                 total))
        per = -(-total // parallelism)  # ceil
        # $sort on _id pins a stable document order so the independent
        # per-partition cursors neither overlap nor leave gaps
        return [ReadTask(make([{"$sort": {"_id": 1}},
                               {"$skip": i * per}, {"$limit": per}]), meta)
                for i in range(parallelism)]


def _default_mongo_client(uri: str) -> Callable[[], Any]:
    def factory():
        try:
            import pymongo
        except ImportError as e:
            raise ImportError(
                "read_mongo requires pymongo (not in this image); pass "
                "client_factory=... to supply a client") from e
        return pymongo.MongoClient(uri)
    return factory


def _close_quietly(client: Any) -> None:
    try:
        client.close()
    except Exception:
        pass


