"""Datasources: pluggable readers/writers producing/consuming blocks.

Reference: ``python/ray/data/datasource/`` — ``Datasource.get_read_tasks`` returns
serializable ``ReadTask`` thunks that execute remotely and yield blocks;
``file_based_datasource.py`` is the shared framework for parquet/csv/json/numpy.
"""

from __future__ import annotations

import glob as globlib
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from .block import Block, BlockAccessor, BlockMetadata, VALUE_COL


@dataclass
class ReadTask:
    """A serializable zero-arg callable producing an iterable of blocks, plus
    metadata estimated at planning time (before any data is read)."""
    read_fn: Callable[[], Iterable[Block]]
    metadata: BlockMetadata

    def __call__(self) -> Iterable[Block]:
        return self.read_fn()


class Datasource:
    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


class RangeDatasource(Datasource):
    def __init__(self, n: int, *, tensor_shape: Optional[tuple] = None):
        self._n = n
        self._tensor_shape = tensor_shape

    def estimate_inmemory_data_size(self):
        per = 8 if not self._tensor_shape else 8 * int(np.prod(self._tensor_shape))
        return self._n * per

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self._n)) if self._n else 1
        tasks = []
        chunk = -(-self._n // parallelism) if self._n else 0
        shape = self._tensor_shape
        for i in range(parallelism):
            lo, hi = i * chunk, min((i + 1) * chunk, self._n)
            if lo >= hi:
                break

            def make(lo=lo, hi=hi):
                if shape is None:
                    return [pa.table({"id": pa.array(range(lo, hi), type=pa.int64())})]
                data = np.stack([np.full(shape, v, dtype=np.int64) for v in range(lo, hi)])
                return [BlockAccessor.for_block(
                    [{"data": row} for row in data]).to_arrow()]

            nbytes = (hi - lo) * (8 if shape is None else 8 * int(np.prod(shape)))
            tasks.append(ReadTask(make, BlockMetadata(num_rows=hi - lo, size_bytes=nbytes)))
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: List[Any]):
        self._items = items

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self._items)
        parallelism = max(1, min(parallelism, n)) if n else 1
        chunk = -(-n // parallelism) if n else 0
        tasks = []
        for i in range(parallelism):
            part = self._items[i * chunk:(i + 1) * chunk]
            if not part:
                break

            def make(part=part):
                if part and isinstance(part[0], dict):
                    return [BlockAccessor.for_block(part).to_arrow()]
                return [part]

            tasks.append(ReadTask(make, BlockMetadata(num_rows=len(part), size_bytes=None)))
        return tasks


class BlocksDatasource(Datasource):
    """Pre-materialized in-memory blocks (from_pandas / from_arrow / from_numpy)."""

    def __init__(self, blocks: List[Block]):
        self._blocks = blocks

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        tasks = []
        for b in self._blocks:
            acc = BlockAccessor.for_block(b)
            tasks.append(ReadTask(lambda b=b: [b], acc.metadata()))
        return tasks


def _expand_paths(paths, ext: Optional[str]) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{ext}" if ext else "*")
            out.extend(sorted(f for f in globlib.glob(pat, recursive=True)
                              if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class FileBasedDatasource(Datasource):
    """Framework for path-list datasources — one or more files per read task.

    Reference: ``python/ray/data/datasource/file_based_datasource.py``.
    """

    _FILE_EXTENSION: Optional[str] = None

    def __init__(self, paths, **reader_args):
        self._paths = _expand_paths(paths, self._FILE_EXTENSION)
        self._reader_args = reader_args

    def _read_file(self, path: str) -> Iterable[Block]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self):
        try:
            return sum(os.path.getsize(p) for p in self._paths)
        except OSError:
            return None

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self._paths)
        parallelism = max(1, min(parallelism, n))
        per = -(-n // parallelism)
        tasks = []
        for i in range(parallelism):
            chunk = self._paths[i * per:(i + 1) * per]
            if not chunk:
                break
            read_file = self._read_file

            def make(chunk=chunk, read_file=read_file):
                def gen():
                    for p in chunk:
                        yield from read_file(p)
                return gen()

            size = None
            try:
                size = sum(os.path.getsize(p) for p in chunk)
            except OSError:
                pass
            tasks.append(ReadTask(make, BlockMetadata(num_rows=None, size_bytes=size,
                                                      input_files=chunk)))
        return tasks


class ParquetDatasource(FileBasedDatasource):
    _FILE_EXTENSION = ".parquet"

    def _read_file(self, path):
        import pyarrow.parquet as pq
        columns = self._reader_args.get("columns")
        yield pq.read_table(path, columns=columns)


class CSVDatasource(FileBasedDatasource):
    _FILE_EXTENSION = ".csv"

    def _read_file(self, path):
        from pyarrow import csv as pcsv
        yield pcsv.read_csv(path, **self._reader_args)


class JSONDatasource(FileBasedDatasource):
    _FILE_EXTENSION = ".json"

    def _read_file(self, path):
        from pyarrow import json as pjson
        yield pjson.read_json(path)


class NumpyDatasource(FileBasedDatasource):
    _FILE_EXTENSION = ".npy"

    def _read_file(self, path):
        arr = np.load(path, allow_pickle=False)
        yield BlockAccessor.for_block([{"data": row} for row in arr]).to_arrow()


class BinaryDatasource(FileBasedDatasource):
    _FILE_EXTENSION = None

    def _read_file(self, path):
        with open(path, "rb") as f:
            data = f.read()
        yield pa.table({"bytes": pa.array([data], type=pa.binary()),
                        "path": pa.array([path])})


class TextDatasource(FileBasedDatasource):
    _FILE_EXTENSION = None

    def _read_file(self, path):
        with open(path, "r", errors="replace") as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": pa.array(lines)})


# ---------------------------------------------------------------------------
# Write path
# ---------------------------------------------------------------------------

def write_block(block: Block, path: str, file_format: str, index: int,
                **writer_args) -> str:
    os.makedirs(path, exist_ok=True)
    acc = BlockAccessor.for_block(block)
    fname = os.path.join(path, f"part-{index:06d}.{file_format}")
    if file_format == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(acc.to_arrow(), fname, **writer_args)
    elif file_format == "csv":
        from pyarrow import csv as pcsv
        pcsv.write_csv(acc.to_arrow(), fname)
    elif file_format == "json":
        df = acc.to_pandas()
        df.to_json(fname, orient="records", lines=True)
    elif file_format == "npy":
        cols = acc.to_numpy()
        key = "data" if "data" in cols else list(cols)[0]
        np.save(fname[:-4], cols[key])
    else:
        raise ValueError(f"unknown write format {file_format}")
    return fname
