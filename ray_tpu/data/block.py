"""Block model: a Dataset is a list of object-store-resident blocks.

Reference: ``python/ray/data/block.py`` — a block is an Arrow table, a pandas
DataFrame, or a plain Python list ("simple" block); ``BlockAccessor`` gives a
uniform interface over the three formats, and ``BlockMetadata`` travels with
every block ref so the driver can plan without fetching data.

Canonical format here is **pyarrow.Table** (zero-copy through the shm object
store); list blocks hold arbitrary Python rows; pandas is converted lazily.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = Union[pa.Table, List[Any], "pandas.DataFrame"]  # noqa: F821

# Column name used when wrapping bare values (ints, arrays) into tabular form,
# mirroring the reference's TENSOR_COLUMN_NAME/"item" convention.
VALUE_COL = "item"


@dataclass
class BlockMetadata:
    num_rows: Optional[int]
    size_bytes: Optional[int]
    schema: Optional[Any] = None
    input_files: List[str] = field(default_factory=list)
    exec_stats: Optional[dict] = None


def _is_pandas(block) -> bool:
    try:
        import pandas as pd
        return isinstance(block, pd.DataFrame)
    except ImportError:  # pragma: no cover
        return False


class BlockAccessor:
    """Uniform view over arrow / pandas / list blocks."""

    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- shape ---------------------------------------------------------------
    def num_rows(self) -> int:
        b = self._block
        if isinstance(b, pa.Table):
            return b.num_rows
        if _is_pandas(b):
            return len(b)
        return len(b)

    def size_bytes(self) -> int:
        b = self._block
        if isinstance(b, pa.Table):
            return b.nbytes
        if _is_pandas(b):
            return int(b.memory_usage(deep=True).sum())
        # rough estimate for simple blocks
        import sys
        return sum(sys.getsizeof(r) for r in b) if len(b) < 10_000 else len(b) * 64

    def schema(self):
        b = self._block
        if isinstance(b, pa.Table):
            return b.schema
        if _is_pandas(b):
            return pa.Schema.from_pandas(b)
        return type(b[0]).__name__ if b else None

    def metadata(self, input_files: Optional[List[str]] = None) -> BlockMetadata:
        return BlockMetadata(num_rows=self.num_rows(), size_bytes=self.size_bytes(),
                             schema=self.schema(), input_files=input_files or [])

    # -- conversion ----------------------------------------------------------
    def to_arrow(self) -> pa.Table:
        b = self._block
        if isinstance(b, pa.Table):
            return b
        if _is_pandas(b):
            return pa.Table.from_pandas(b, preserve_index=False)
        # simple block: dict rows → columns; bare values → VALUE_COL
        if b and isinstance(b[0], dict):
            cols: Dict[str, list] = {k: [] for k in b[0]}
            for row in b:
                for k in cols:
                    cols[k].append(row.get(k))
            return pa.table({k: _to_arrow_array(v) for k, v in cols.items()})
        return pa.table({VALUE_COL: _to_arrow_array(list(b))})

    def to_pandas(self):
        import pandas as pd
        b = self._block
        if _is_pandas(b):
            return b
        if isinstance(b, pa.Table):
            return b.to_pandas()
        return self.to_arrow().to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
        t = self.to_arrow()
        names = columns or t.column_names
        out = {}
        for name in names:
            col = t.column(name)
            out[name] = _column_to_numpy(col)
        return out

    def to_batch(self, batch_format: str):
        if batch_format in ("numpy", "numpy_dict", "default"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.to_arrow()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # -- row access ----------------------------------------------------------
    def iter_rows(self) -> Iterator[Any]:
        b = self._block
        if isinstance(b, list):
            yield from b
            return
        t = self.to_arrow()
        cols = t.column_names
        if cols == [VALUE_COL]:
            for v in t.column(VALUE_COL).to_pylist():
                yield v
            return
        data = {}
        for c in cols:
            col = t.column(c)
            if isinstance(col.type, getattr(pa, "FixedShapeTensorType", ())):
                data[c] = list(_column_to_numpy(col))
            else:
                data[c] = col.to_pylist()
        for i in range(t.num_rows):
            yield {c: data[c][i] for c in cols}

    def slice(self, start: int, end: int) -> Block:
        b = self._block
        if isinstance(b, pa.Table):
            return b.slice(start, end - start)
        if _is_pandas(b):
            return b.iloc[start:end]
        return b[start:end]

    def take(self, n: int) -> List[Any]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def sample(self, n: int, rng: np.random.Generator) -> List[Any]:
        rows = list(self.iter_rows())
        if not rows:
            return []
        idx = rng.choice(len(rows), size=min(n, len(rows)), replace=False)
        return [rows[i] for i in idx]


def _to_arrow_array(values: list) -> pa.Array:
    if values and isinstance(values[0], np.ndarray):
        return _tensor_array(values)
    try:
        return pa.array(values)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        return _tensor_array([np.asarray(v) for v in values])


def _tensor_array(arrs: List[np.ndarray]) -> pa.Array:
    """Fixed-shape tensor column (Arrow FixedShapeTensorType when uniform)."""
    shapes = {a.shape for a in arrs}
    if len(shapes) == 1 and arrs[0].ndim >= 1:
        stacked = np.stack(arrs)
        try:
            return pa.FixedShapeTensorArray.from_numpy_ndarray(stacked)
        except (AttributeError, pa.ArrowNotImplementedError):
            return pa.array(stacked.reshape(len(arrs), -1).tolist())
    return pa.array([a.tolist() for a in arrs])


def _column_to_numpy(col: pa.ChunkedArray) -> np.ndarray:
    if isinstance(col.type, getattr(pa, "FixedShapeTensorType", ())):
        combined = col.combine_chunks()
        return combined.to_numpy_ndarray()
    try:
        return col.to_numpy(zero_copy_only=False)
    except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
        return np.asarray(col.to_pylist(), dtype=object)


def batch_to_block(batch) -> Block:
    """Normalize a user-function return (dict of arrays / DataFrame / Table /
    list) into a block."""
    if isinstance(batch, pa.Table):
        return batch
    if _is_pandas(batch):
        return pa.Table.from_pandas(batch, preserve_index=False)
    if isinstance(batch, dict):
        n = None
        for v in batch.values():
            ln = len(v)
            if n is not None and ln != n:
                raise ValueError("batch columns have unequal lengths")
            n = ln
        return pa.table({k: _to_arrow_array(list(np.asarray(v)) if isinstance(v, np.ndarray) else list(v))
                         for k, v in batch.items()})
    if isinstance(batch, list):
        return batch
    raise TypeError(f"cannot convert batch of type {type(batch)} to a block")


class DelegatingBlockBuilder:
    """Accumulates rows or batches and emits blocks of bounded size.

    Reference: ``python/ray/data/_internal/delegating_block_builder.py``.
    """

    def __init__(self):
        self._rows: List[Any] = []
        self._tables: List[pa.Table] = []

    def add(self, row: Any):
        self._rows.append(row)

    def add_block(self, block: Block):
        acc = BlockAccessor.for_block(block)
        if isinstance(block, list):
            self._rows.extend(block)
        else:
            self._tables.append(acc.to_arrow())

    def num_rows(self) -> int:
        return len(self._rows) + sum(t.num_rows for t in self._tables)

    def build(self) -> Block:
        if self._tables and not self._rows:
            return pa.concat_tables(self._tables) if len(self._tables) > 1 else self._tables[0]
        if self._rows and not self._tables:
            if self._rows and isinstance(self._rows[0], dict):
                return BlockAccessor.for_block(self._rows).to_arrow()
            return list(self._rows)
        if not self._rows and not self._tables:
            return pa.table({})
        # mixed: go through arrow
        parts = list(self._tables)
        if self._rows:
            parts.append(BlockAccessor.for_block(self._rows).to_arrow())
        return pa.concat_tables(parts)
