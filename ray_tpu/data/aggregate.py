"""Aggregation functions for groupby/global aggregation.

Reference: ``python/ray/data/aggregate.py`` — ``AggregateFn`` with
init/accumulate/merge/finalize; built-ins Count/Sum/Min/Max/Mean/Std.
Implemented here over Arrow compute on whole blocks (vectorized per block,
merged across blocks).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np
import pyarrow.compute as pc


class AggregateFn:
    def __init__(self, name: str,
                 block_acc: Callable,  # (arrow table) -> partial
                 merge: Callable,  # (partial, partial) -> partial
                 finalize: Callable = lambda a: a):
        self.name = name
        self.block_acc = block_acc
        self.merge = merge
        self.finalize = finalize


class Count(AggregateFn):
    def __init__(self):
        super().__init__("count()", lambda t: t.num_rows, lambda a, b: a + b)


class Sum(AggregateFn):
    def __init__(self, on: str):
        super().__init__(f"sum({on})",
                         lambda t: pc.sum(t.column(on)).as_py() or 0,
                         lambda a, b: a + b)


class Min(AggregateFn):
    def __init__(self, on: str):
        super().__init__(f"min({on})",
                         lambda t: pc.min(t.column(on)).as_py(),
                         lambda a, b: min(x for x in (a, b) if x is not None)
                         if (a is not None or b is not None) else None)


class Max(AggregateFn):
    def __init__(self, on: str):
        super().__init__(f"max({on})",
                         lambda t: pc.max(t.column(on)).as_py(),
                         lambda a, b: max(x for x in (a, b) if x is not None)
                         if (a is not None or b is not None) else None)


class Mean(AggregateFn):
    def __init__(self, on: str):
        def acc(t):
            s = pc.sum(t.column(on)).as_py() or 0
            return (s, t.num_rows)
        super().__init__(f"mean({on})", acc,
                         lambda a, b: (a[0] + b[0], a[1] + b[1]),
                         lambda a: a[0] / a[1] if a[1] else None)


class Std(AggregateFn):
    """Welford-style mergeable variance (ddof=1, matching the reference)."""

    def __init__(self, on: str, ddof: int = 1):
        def acc(t):
            arr = t.column(on).to_numpy(zero_copy_only=False).astype(np.float64)
            n = len(arr)
            if n == 0:
                return (0, 0.0, 0.0)
            m = float(arr.mean())
            m2 = float(((arr - m) ** 2).sum())
            return (n, m, m2)

        def merge(a, b):
            na, ma, m2a = a
            nb, mb, m2b = b
            if na == 0:
                return b
            if nb == 0:
                return a
            n = na + nb
            delta = mb - ma
            m = ma + delta * nb / n
            m2 = m2a + m2b + delta * delta * na * nb / n
            return (n, m, m2)

        def fin(a):
            n, _, m2 = a
            if n - ddof <= 0:
                return None
            return float(np.sqrt(m2 / (n - ddof)))

        super().__init__(f"std({on})", acc, merge, fin)


class AbsMax(AggregateFn):
    def __init__(self, on: str):
        super().__init__(f"abs_max({on})",
                         lambda t: pc.max(pc.abs(t.column(on))).as_py(),
                         lambda a, b: max(x for x in (a, b) if x is not None)
                         if (a is not None or b is not None) else None)
