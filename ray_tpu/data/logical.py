"""Logical plan for ray_tpu.data.

Reference: ``python/ray/data/_internal/logical/`` — operators describe *what*
to compute; the planner (``planner.py``) lowers them to physical operators and
applies fusion rules (consecutive map-type ops fuse into one task per block,
mirroring ``_internal/logical/rules/operator_fusion.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .datasource import Datasource


class LogicalOp:
    """A node in the logical DAG; ``input_op`` forms a chain, extra inputs
    (union/zip) are in ``extra_inputs``."""

    input_op: Optional["LogicalOp"] = None
    extra_inputs: List["LogicalOp"] = []

    def name(self) -> str:
        return type(self).__name__

    def chain(self) -> List["LogicalOp"]:
        out: List[LogicalOp] = []
        node: Optional[LogicalOp] = self
        while node is not None:
            out.append(node)
            node = node.input_op
        return list(reversed(out))


@dataclass
class Read(LogicalOp):
    datasource: Datasource
    parallelism: int = -1
    input_op: Optional[LogicalOp] = None
    extra_inputs: List[LogicalOp] = field(default_factory=list)

    def name(self):
        return f"Read{self.datasource.name}"


@dataclass
class InputData(LogicalOp):
    """Already-materialized (ref, metadata) bundles."""
    bundles: List[Any]
    input_op: Optional[LogicalOp] = None
    extra_inputs: List[LogicalOp] = field(default_factory=list)


@dataclass
class AbstractMap(LogicalOp):
    fn: Callable = None
    fn_args: Tuple = ()
    fn_kwargs: Dict[str, Any] = field(default_factory=dict)
    # "tasks" or ("actors", min, max) for class-based fns
    compute: Any = "tasks"
    fn_constructor_args: Tuple = ()
    input_op: Optional[LogicalOp] = None
    extra_inputs: List[LogicalOp] = field(default_factory=list)
    ray_remote_args: Dict[str, Any] = field(default_factory=dict)


@dataclass
class MapBatches(AbstractMap):
    batch_size: Optional[int] = None
    batch_format: str = "default"
    zero_copy_batch: bool = False

    def name(self):
        return f"MapBatches({getattr(self.fn, '__name__', 'fn')})"


@dataclass
class MapRows(AbstractMap):
    def name(self):
        return f"Map({getattr(self.fn, '__name__', 'fn')})"


@dataclass
class Filter(AbstractMap):
    def name(self):
        return f"Filter({getattr(self.fn, '__name__', 'fn')})"


@dataclass
class FlatMap(AbstractMap):
    def name(self):
        return f"FlatMap({getattr(self.fn, '__name__', 'fn')})"


@dataclass
class Limit(LogicalOp):
    n: int = 0
    input_op: Optional[LogicalOp] = None
    extra_inputs: List[LogicalOp] = field(default_factory=list)


# -- all-to-all ops ---------------------------------------------------------

@dataclass
class AbstractAllToAll(LogicalOp):
    input_op: Optional[LogicalOp] = None
    extra_inputs: List[LogicalOp] = field(default_factory=list)


@dataclass
class RandomShuffle(AbstractAllToAll):
    seed: Optional[int] = None
    num_outputs: Optional[int] = None


@dataclass
class RandomizeBlockOrder(AbstractAllToAll):
    seed: Optional[int] = None


@dataclass
class Repartition(AbstractAllToAll):
    num_outputs: int = 1
    shuffle: bool = False


@dataclass
class Sort(AbstractAllToAll):
    key: Any = None
    descending: bool = False


@dataclass
class Aggregate(AbstractAllToAll):
    key: Optional[str] = None
    aggs: List[Any] = field(default_factory=list)


@dataclass
class Union(LogicalOp):
    input_op: Optional[LogicalOp] = None
    extra_inputs: List[LogicalOp] = field(default_factory=list)


@dataclass
class Zip(LogicalOp):
    input_op: Optional[LogicalOp] = None
    extra_inputs: List[LogicalOp] = field(default_factory=list)


@dataclass
class Write(LogicalOp):
    path: str = ""
    file_format: str = "parquet"
    writer_args: Dict[str, Any] = field(default_factory=dict)
    input_op: Optional[LogicalOp] = None
    extra_inputs: List[LogicalOp] = field(default_factory=list)
