"""ray_tpu.data — distributed datasets with streaming execution.

Reference surface: ``python/ray/data/__init__.py`` — read_* constructors,
from_* converters, Dataset, aggregations, DataContext.
"""

from __future__ import annotations

from typing import Any, List, Optional

from . import logical as L
from .aggregate import AbsMax, AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import Block, BlockAccessor, BlockMetadata
from .context import DataContext
from .dataset import Dataset, GroupedData
from .datasource import (BinaryDatasource, BlocksDatasource, CSVDatasource,
                         Datasource, ImageDatasource, ItemsDatasource,
                         JSONDatasource, NumpyDatasource, ParquetDatasource,
                         RangeDatasource, ReadTask, SQLDatasource,
                         TextDatasource, TFRecordDatasource)
from .iterator import DataIterator


def read_datasource(datasource: Datasource, *, parallelism: int = -1) -> Dataset:
    return Dataset(L.Read(datasource=datasource, parallelism=parallelism))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1) -> Dataset:
    return read_datasource(RangeDatasource(n, tensor_shape=tuple(shape)),
                           parallelism=parallelism)


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    return read_datasource(ItemsDatasource(list(items)), parallelism=parallelism)


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    return read_datasource(BlocksDatasource(dfs))


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return read_datasource(BlocksDatasource(tables))


def from_numpy(arrays) -> Dataset:
    import numpy as np
    if not isinstance(arrays, list):
        arrays = [arrays]
    blocks = [BlockAccessor.for_block([{"data": row} for row in a]).to_arrow()
              for a in arrays]
    return read_datasource(BlocksDatasource(blocks))


def from_huggingface(hf_dataset) -> Dataset:
    """Zero-copy from a HuggingFace datasets.Dataset (arrow-backed)."""
    table = hf_dataset.data.table if hasattr(hf_dataset.data, "table") \
        else hf_dataset.data
    return from_arrow(table.combine_chunks())


def from_torch(torch_dataset, *, block_size: int = 1000) -> Dataset:
    """Materialize a torch ``Dataset`` (map- or iterable-style) into
    blocks (reference: ``read_api.py`` ``from_torch``). A single-value
    item becomes a row ``{"item": value}``; a tuple item (the
    ``(features, label)`` convention) becomes ``{"item_0": ...,
    "item_1": ...}`` columns. Tensors convert to numpy so the blocks
    stay framework-neutral."""

    def to_np(v):
        return v.numpy() if hasattr(v, "numpy") else v

    def to_row(x):
        if isinstance(x, (tuple, list)):
            # mixed-type tuples (tensor, int-label) cannot share one
            # Arrow column — split into item_i fields
            return {f"item_{i}": to_np(v) for i, v in enumerate(x)}
        return {"item": to_np(x)}

    from builtins import range as _range  # this module shadows range()

    if hasattr(torch_dataset, "__len__") and hasattr(torch_dataset,
                                                     "__getitem__"):
        # map-style: index explicitly — plain iteration would fall back
        # to the sequence protocol, which never terminates on datasets
        # that don't raise IndexError
        items = (torch_dataset[i] for i in _range(len(torch_dataset)))
    elif hasattr(torch_dataset, "__iter__"):
        items = iter(torch_dataset)
    else:
        raise ValueError(
            "from_torch needs an iterable-style dataset (__iter__) or a "
            "map-style one with BOTH __len__ and __getitem__ — a bare "
            "__getitem__ would be iterated via the sequence protocol, "
            "which never terminates when IndexError is never raised")
    blocks, cur = [], []
    for item in items:
        cur.append(to_row(item))
        if len(cur) >= block_size:
            blocks.append(BlockAccessor.for_block(cur).to_arrow())
            cur = []
    if cur or not blocks:
        blocks.append(BlockAccessor.for_block(cur).to_arrow())
    return read_datasource(BlocksDatasource(blocks))


def read_parquet(paths, *, parallelism: int = -1, columns=None) -> Dataset:
    return read_datasource(ParquetDatasource(paths, columns=columns),
                           parallelism=parallelism)


def read_csv(paths, *, parallelism: int = -1, **arrow_csv_args) -> Dataset:
    return read_datasource(CSVDatasource(paths, **arrow_csv_args),
                           parallelism=parallelism)


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(JSONDatasource(paths), parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(NumpyDatasource(paths), parallelism=parallelism)


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(BinaryDatasource(paths), parallelism=parallelism)


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    return read_datasource(TextDatasource(paths), parallelism=parallelism)


def read_tfrecords(paths, *, parallelism: int = -1, raw: bool = False) -> Dataset:
    return read_datasource(TFRecordDatasource(paths, raw=raw),
                           parallelism=parallelism)


def read_orc(paths, *, parallelism: int = -1, columns=None) -> Dataset:
    from .datasource import ORCDatasource
    return read_datasource(ORCDatasource(paths, columns=columns),
                           parallelism=parallelism)


def read_webdataset(paths, *, parallelism: int = -1) -> Dataset:
    from .datasource import WebDatasetDatasource
    return read_datasource(WebDatasetDatasource(paths),
                           parallelism=parallelism)


def read_sql(sql: str, connection_factory, *, shard_queries=None,
             parallelism: int = -1) -> Dataset:
    return read_datasource(
        SQLDatasource(sql, connection_factory, shard_queries=shard_queries),
        parallelism=parallelism)


def read_images(paths, *, size=None, mode=None, parallelism: int = -1) -> Dataset:
    return read_datasource(ImageDatasource(paths, size=size, mode=mode),
                           parallelism=parallelism)


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline=None, client_factory=None,
               parallelism: int = -1) -> Dataset:
    """Reference: ``ray.data.read_mongo`` — see MongoDatasource for the
    pymongo/client_factory contract on this no-pymongo image."""
    from .datasource import MongoDatasource
    return read_datasource(
        MongoDatasource(uri, database, collection, pipeline=pipeline,
                        client_factory=client_factory),
        parallelism=parallelism)


__all__ = [
    "Dataset", "GroupedData", "DataContext", "DataIterator", "Datasource",
    "ReadTask", "Block", "BlockAccessor", "BlockMetadata",
    "AggregateFn", "Count", "Sum", "Min", "Max", "Mean", "Std", "AbsMax",
    "read_datasource", "range", "range_tensor", "from_items", "from_pandas",
    "from_arrow", "from_numpy", "from_huggingface", "from_torch", "read_parquet", "read_csv",
    "read_json", "read_numpy", "read_binary_files", "read_text",
    "read_tfrecords", "read_sql", "read_images", "read_orc", "read_mongo",
    "read_webdataset", "TFRecordDatasource", "SQLDatasource",
    "ImageDatasource",
]

# Usage telemetry: which libraries a cluster actually uses (reference:
# usage_lib.record_library_usage at import time).  Never raises.
from ray_tpu.util.usage_stats import record_library_usage as _rlu
_rlu("data")
del _rlu
